"""pyspark-BigDL API compatibility: `bigdl.nn.layer`.

Parity: reference pyspark/bigdl/nn/layer.py:118 (`Layer`), :671
(`Container`), :696 (`Model`), :1112 (`Sequential`) plus the per-layer
classes. In the reference each class forwards its constructor args over
py4j to a JVM factory; here each class builds the equivalent
`bigdl_tpu.nn` module in-process and stores it in `.value` (the same
field the reference uses for the JVM handle).

Semantics preserved from the pyspark surface:
  - NCHW is the default data format (the reference's Torch heritage);
    spatial layers pass `data_format="NCHW"` down to the TPU-native
    modules, which transpose once at trace time.
  - `init_weight` / `init_bias` ndarrays use the reference layouts
    (Linear: (out, in); conv: (group, out, in, kh, kw)) and are
    transposed into the native HWIO/(in,out) layouts.
  - Regularizers attach per-layer as in the reference
    (wRegularizer/bRegularizer).
  - `propagate_back`, `init_grad_weight`, `init_grad_bias` are accepted
    and ignored: autodiff owns the backward pass, and gradients are not
    stateful buffers here.

Layers with a pyspark-specific signature are defined explicitly below;
every other `bigdl_tpu.nn` layer is exposed through a generated
passthrough class with the same constructor (the native arg names match
the pyspark ones — both were derived from the same Scala createX
factories).
"""

from __future__ import annotations

import sys
from typing import Any, List, Optional

import numpy as np

import bigdl_tpu.nn as _nn
from bigdl.util.common import JTensor, Sample, to_list

__all__ = ["Layer", "Container", "Model", "Sequential", "Node", "Identity"]


def _as_ndarray(x):
    if isinstance(x, JTensor):
        return x.to_ndarray()
    return np.asarray(x)


def _jnp(x):
    import jax.numpy as jnp
    return jnp.asarray(_as_ndarray(x))


class Node(object):
    """Reference pyspark/bigdl/nn/layer.py Node — a vertex in the graph
    DSL. Wraps a `bigdl_tpu.nn.Node`."""

    def __init__(self, tpu_node, bigdl_type="float"):
        self.value = tpu_node
        self.bigdl_type = bigdl_type

    @classmethod
    def of(cls, tpu_node, bigdl_type="float"):
        return cls(tpu_node, bigdl_type)

    def element(self):
        return Layer.of(self.value.module)

    def remove_pre_edges(self):
        raise NotImplementedError(
            "remove_pre_edges: rebuild the graph instead (functional DSL)")


class Layer(object):
    """Reference pyspark/bigdl/nn/layer.py:118 Layer — base wrapper.

    `.value` is the in-process `bigdl_tpu.nn.Module` (where the reference
    stores the py4j JVM handle).
    """

    def __init__(self, jvalue=None, bigdl_type="float", *args):
        if jvalue is None:
            raise ValueError(
                f"{type(self).__name__}: no backing module. Compat layers "
                "must pass the constructed bigdl_tpu module as jvalue.")
        self.value = jvalue
        self.bigdl_type = bigdl_type

    # -- construction helpers -------------------------------------------
    @classmethod
    def of(cls, tpu_module, bigdl_type="float"):
        """Wrap an existing bigdl_tpu module (reference Layer.of)."""
        layer = Layer(tpu_module, bigdl_type)
        return layer

    # -- identity -------------------------------------------------------
    def set_name(self, name):
        self.value.name = name
        return self

    def name(self):
        return self.value.name

    def __str__(self):
        return str(self.value)

    def set_seed(self, seed=123):
        """Reference setModelSeed: seeds the global init RNG."""
        from bigdl_tpu.utils.random_generator import RNG as _rng
        _rng.setSeed(seed)
        return self

    def get_dtype(self):
        return "float32" if self.bigdl_type == "float" else "float64"

    # -- compute --------------------------------------------------------
    def _ensure_params(self):
        self.value.ensure_params()

    def _rng(self):
        """Per-call jax PRNG key drawn from the seeded global generator
        (the reference's dropout masks come from the JVM RNG the same
        way: seeded via set_seed, advancing per call)."""
        import jax as _jax

        from bigdl_tpu.utils.random_generator import RNG as _rng
        return _jax.random.PRNGKey(int(_rng.randint(0, 2 ** 31 - 1)))

    def forward(self, input):
        """Debug-only single forward (reference modelForward)."""
        inputs = [_jnp(i) for i in to_list(input)]
        out = self.value.forward(inputs[0] if len(inputs) == 1 else inputs,
                                 rng=self._rng())
        return self._convert_output(out)

    def backward(self, input, grad_output):
        """Debug-only backward: grad of <output, grad_output> w.r.t. the
        input, computed by autodiff (reference modelBackward). Parameter
        gradients are accumulated on the side (reference
        accGradParameters) for `update_parameters`."""
        import jax

        from bigdl_tpu.nn.module import functional_apply
        inputs = [_jnp(i) for i in to_list(input)]
        gouts = [_jnp(g) for g in to_list(grad_output)]
        x = inputs[0] if len(inputs) == 1 else inputs
        g = gouts[0] if len(gouts) == 1 else gouts
        self._ensure_params()
        params = self.value.parameters()

        rng = self._rng()
        mstate = self.value._state  # live BN running stats, not init's

        def fwd(p, xx):
            out, _ = functional_apply(
                self.value, p, xx, rng=rng, state=mstate,
                training=self.value.training_mode)
            return out

        _, vjp = jax.vjp(fwd, params, x)
        gparams, gin = vjp(g)
        acc = getattr(self, "_acc_grads", None)
        self._acc_grads = gparams if acc is None else \
            jax.tree_util.tree_map(lambda a, b: a + b, acc, gparams)
        return self._convert_output(gin)

    def zero_grad_parameters(self):
        """Reset the gradient accumulator `backward` fills (reference
        zeroGradParameters)."""
        self._acc_grads = None
        return self

    def reset(self):
        """Drop materialized parameters so the next use re-initializes
        them (reference `reset` re-draws weights in place; the functional
        design re-draws lazily at the next ensure_params)."""
        def clear(m):
            m._params = None
            m._state = {}
            for c in getattr(m, "children", []):
                clear(c)
            for n in getattr(m, "exec_order", []):
                clear(n.module)
        clear(self.value)
        return self

    def update_parameters(self, learning_rate):
        """Apply the accumulated parameter gradients: params -= lr * grad
        (reference updateParameters — the manual torch-style loop:
        forward / backward / update_parameters / zero_grad_parameters).
        `backward` accumulates parameter gradients by autodiff; here they
        are folded into the module's stateful params."""
        import jax
        acc = getattr(self, "_acc_grads", None)
        if acc is None:
            raise RuntimeError(
                "update_parameters: no accumulated gradients — call "
                "backward(input, grad_output) first")
        self._ensure_params()
        new = jax.tree_util.tree_map(
            lambda p, g: p - learning_rate * g,
            self.value.parameters(), acc)
        self.value.set_params(new)
        return self

    @staticmethod
    def _convert_output(output):
        if isinstance(output, (list, tuple)):
            return [np.asarray(o) for o in output]
        try:
            from bigdl_tpu.utils.table import Table
            if isinstance(output, Table):
                return [np.asarray(o) for o in output.values()]
        except Exception:
            pass
        return np.asarray(output)

    # -- parameters -----------------------------------------------------
    def parameters(self):
        """Layer-name -> {'weight': ndarray, ...} (reference
        modelGetParameters). Layouts are the native TPU ones (HWIO etc.);
        see docs/MIGRATION.md."""
        self._ensure_params()
        tree = self.value.parameters()
        flat = {}

        def walk(prefix, node):
            leaves = {k: v for k, v in node.items()
                      if not isinstance(v, dict)}
            if leaves:
                flat[prefix or self.name()] = {
                    k: np.asarray(v) for k, v in leaves.items()}
            for k, v in node.items():
                if isinstance(v, dict):
                    walk(f"{prefix}.{k}" if prefix else k, v)

        walk("", tree if isinstance(tree, dict) else {"weight": tree})
        return flat

    def get_weights(self):
        """Flat list of parameter ndarrays in layer order (reference
        getWeights). Native layouts."""
        self._ensure_params()
        import jax
        leaves = jax.tree_util.tree_leaves(self.value.parameters())
        return [np.asarray(l) for l in leaves]

    def set_weights(self, weights):
        """Inverse of get_weights (reference setWeights)."""
        self._ensure_params()
        import jax
        tree = self.value.parameters()
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if len(weights) != len(leaves):
            raise ValueError(
                f"set_weights: expected {len(leaves)} arrays, got "
                f"{len(weights)}")
        import jax.numpy as jnp
        new = [jnp.asarray(np.asarray(w), l.dtype).reshape(l.shape)
               for w, l in zip(weights, leaves)]
        self.value.set_params(jax.tree_util.tree_unflatten(treedef, new))
        return self

    # -- training-mode flags -------------------------------------------
    def training(self, is_training=True):
        if is_training:
            self.value.training()
        else:
            self.value.evaluate()
        return self

    def evaluate(self, *args):
        """With no args: switch to eval mode (reference evaluate()).
        With (val_rdd, batch_size, val_methods): run validation and
        return EvaluatedResult list (reference modelEvaluate)."""
        if not args:
            self.value.evaluate()
            return self
        val_rdd, batch_size, val_methods = args
        from bigdl.util.common import EvaluatedResult
        data = [s._to_tpu_sample() if isinstance(s, Sample) else s
                for s in val_rdd]
        self._ensure_params()
        results = self.value.evaluate_on(data, [m.value for m in val_methods],
                                         batch_size=batch_size)
        out = []
        for r, m in zip(results, val_methods):
            value, total = r.result()  # native contract: (metric, count)
            out.append(EvaluatedResult(float(value), int(total), str(m)))
        return out

    def is_training(self):
        return bool(self.value.training_mode)

    # -- prediction -----------------------------------------------------
    def predict(self, data_rdd, batch_size=32):
        """Predict over a list of Samples / ndarray (reference
        predict/predict_distributed — one in-process path here)."""
        return self.predict_local(data_rdd, batch_size)

    predict_distributed = predict

    def predict_local(self, X, batch_size=32):
        self._ensure_params()
        if isinstance(X, np.ndarray):
            return np.asarray(self.value.predict(_jnp(X),
                                                 batch_size=batch_size))
        data = [s._to_tpu_sample() if isinstance(s, Sample) else s
                for s in X]
        return np.asarray(self.value.predict(data, batch_size=batch_size))

    def predict_class(self, data_rdd, batch_size=32):
        """Class prediction, 1-based as in the reference."""
        self._ensure_params()
        if isinstance(data_rdd, np.ndarray):
            return np.asarray(self.value.predict_class(
                _jnp(data_rdd), batch_size=batch_size))
        data = [s._to_tpu_sample() if isinstance(s, Sample) else s
                for s in data_rdd]
        return np.asarray(self.value.predict_class(data,
                                                   batch_size=batch_size))

    predict_classes = predict_class

    # -- persistence ----------------------------------------------------
    def save(self, path, over_write=False):
        import os
        if not over_write and os.path.exists(path):
            raise RuntimeError(f"file exists: {path} (over_write=False)")
        self._ensure_params()
        from bigdl_tpu.serialization.module_serializer import ModuleSerializer
        ModuleSerializer.save(self.value, path)
        return self

    def saveModel(self, modelPath, weightPath=None, over_write=False):
        return self.save(modelPath, over_write)

    def save_caffe(self, prototxt_path, model_path, use_v2=True,
                   overwrite=False):
        from bigdl_tpu.interop.caffe import CaffePersister
        CaffePersister.persist(prototxt_path, model_path, self.value,
                               use_v2=use_v2, overwrite=overwrite)
        return self

    def save_tensorflow(self, inputs, path, byte_order="little_endian",
                        data_format="nhwc"):
        from bigdl_tpu.interop.tensorflow import TensorflowSaver
        TensorflowSaver.save(self.value, inputs, path,
                             byte_order=byte_order, data_format=data_format)
        return self

    # -- misc parity ----------------------------------------------------
    def quantize(self):
        return Layer.of(self.value.quantize())

    def set_init_method(self, weight_init_method=None, bias_init_method=None):
        m = self.value
        if weight_init_method is not None:
            m.weight_init = getattr(weight_init_method, "value",
                                    weight_init_method)
        if bias_init_method is not None:
            m.bias_init = getattr(bias_init_method, "value", bias_init_method)
        return self

    def freeze(self, names=None):
        """Freeze this layer or the named sub-layers (reference freeze):
        their params pass through stop_gradient in the traced graph, so
        optimizers see zero gradients for them."""
        self.value.freeze(names)
        return self

    def unfreeze(self, names=None):
        self.value.unfreeze(names)
        return self

    def __call__(self, x=None):
        """Graph DSL: layer(node) -> Node (reference createNode). Native
        spelling is `module.inputs(*nodes)` (the Scala `inputs` API)."""
        xs = to_list(x) if x is not None else []
        tpu_nodes = [n.value if isinstance(n, Node) else n for n in xs]
        return Node.of(self.value.inputs(*tpu_nodes))


class SharedStaticUtils(object):
    """Static load/of utilities shared by Layer and Model in the reference
    (pyspark/bigdl/nn/layer.py:49)."""

    @staticmethod
    def load(path, bigdl_type="float"):
        from bigdl_tpu.serialization.module_serializer import ModuleSerializer
        return Layer.of(ModuleSerializer.load(path), bigdl_type)


# Layer inherits the statics the same way the reference mixes them in.
Layer.load = staticmethod(SharedStaticUtils.load)


class Container(Layer):
    """Reference pyspark/bigdl/nn/layer.py:671."""

    def add(self, model):
        self.value.add(model.value)
        return self

    @property
    def layers(self):
        return [Layer.of(m) for m in self.value.children]

    def flattened_layers(self, include_container=False):
        out = []

        def walk(m):
            subs = getattr(m, "children", None)
            if subs:
                if include_container:
                    out.append(m)
                for s in subs:
                    walk(s)
            else:
                out.append(m)

        walk(self.value)
        return [Layer.of(m) for m in out]


class Sequential(Container):
    """Reference pyspark/bigdl/nn/layer.py:1112."""

    def __init__(self, jvalue=None, bigdl_type="float"):
        super().__init__(jvalue or _nn.Sequential(), bigdl_type)


class Concat(Container):
    """Reference createConcat: children outputs joined along the 1-based
    `dimension` (2 = channel under the reference's NCHW activations);
    the native Concat takes a 0-based axis."""

    def __init__(self, dimension=2, jvalue=None, bigdl_type="float"):
        super().__init__(jvalue or _nn.Concat(axis=dimension - 1),
                         bigdl_type)


class Squeeze(Layer):
    """Reference createSqueeze: drop the 1-based `dim`. With
    `num_input_dims` set (batch mode, Squeeze.scala), `dim` is counted
    WITHOUT the batch axis, so the squeezed axis shifts right by one;
    native Squeeze is 0-based."""

    def __init__(self, dim=None, num_input_dims=0, jvalue=None,
                 bigdl_type="float"):
        if dim is None:
            axis = None
        else:
            axis = dim if num_input_dims > 0 else dim - 1
        super().__init__(jvalue or _nn.Squeeze(axis), bigdl_type)


class Select(Layer):
    """Reference createSelect: pick `index` along `dim`, both 1-based
    (negative dim/index count from the end); native Select is 0-based."""

    def __init__(self, dim, index, jvalue=None, bigdl_type="float"):
        axis = dim - 1 if dim > 0 else dim
        idx = index - 1 if index > 0 else index
        super().__init__(jvalue or _nn.Select(axis, idx), bigdl_type)


class Recurrent(Container):
    """Reference createRecurrent: built empty, the cell arrives via
    `.add(cell)` (`Recurrent().add(LSTM(...))`). The native Recurrent
    takes its cell at construction, so the wrapper defers building until
    the add — or accepts a cell directly for the native spelling."""

    def __init__(self, cell=None, jvalue=None, bigdl_type="float"):
        if jvalue is not None:
            super().__init__(jvalue, bigdl_type)
            return
        # `value` is a STABLE wrapper container: outer containers that
        # add() this layer before its cell arrives hold the same object
        # the later add(cell) fills (the reference's JVM container is
        # likewise built up front and mutated)
        super().__init__(_nn.Sequential(name="Recurrent"), bigdl_type)
        if cell is not None:
            self.add(cell)

    def add(self, cell):
        if self.value.children:
            raise ValueError("Recurrent holds exactly one cell")
        self.value.add(_nn.Recurrent(_unwrap(cell)))
        return self


class Model(Container):
    """Graph container (reference pyspark/bigdl/nn/layer.py:696).

    `Model(inputs, outputs)` over `Node`s from the `layer(node)` DSL.
    """

    def __init__(self, inputs=None, outputs=None, jvalue=None,
                 bigdl_type="float", byte_order="little_endian",
                 model_type="bigdl"):
        if jvalue is not None:
            super().__init__(jvalue, bigdl_type)
            return
        if model_type != "bigdl":
            raise NotImplementedError(
                "model_type='tensorflow': use Model.load_tensorflow")
        ins = [n.value if isinstance(n, Node) else n for n in to_list(inputs)]
        outs = [n.value if isinstance(n, Node) else n
                for n in to_list(outputs)]
        super().__init__(_nn.Graph(ins, outs), bigdl_type)

    @staticmethod
    def from_jvalue(jvalue, bigdl_type="float"):
        return Model(jvalue=jvalue, bigdl_type=bigdl_type)

    @staticmethod
    def load(path, bigdl_type="float"):
        return SharedStaticUtils.load(path, bigdl_type)

    @staticmethod
    def loadModel(modelPath, weightPath=None, bigdl_type="float"):
        return SharedStaticUtils.load(modelPath, bigdl_type)

    @staticmethod
    def load_torch(path, bigdl_type="float"):
        from bigdl_tpu.interop.torch_file import TorchFile
        return Layer.of(TorchFile.load_module(path))

    @staticmethod
    def load_caffe(model, defPath, modelPath, match_all=True,
                   bigdl_type="float"):
        from bigdl_tpu.interop.caffe import CaffeLoader
        return Layer.of(CaffeLoader.load(model.value if model else None,
                                         defPath, modelPath,
                                         match_all=match_all))

    @staticmethod
    def load_caffe_model(defPath, modelPath, bigdl_type="float"):
        from bigdl_tpu.interop.caffe import CaffeLoader
        return Layer.of(CaffeLoader.load_caffe(defPath, modelPath))

    @staticmethod
    def load_tensorflow(path, inputs, outputs, byte_order="little_endian",
                        bin_file=None, bigdl_type="float"):
        from bigdl_tpu.interop.tensorflow import TensorflowLoader
        return Layer.of(TensorflowLoader.load(path, inputs, outputs,
                                              byte_order=byte_order,
                                              bin_file=bin_file))

    @staticmethod
    def load_keras(json_path=None, hdf5_path=None, by_name=False):
        from bigdl_tpu.interop.keras_converter import load_keras
        return Layer.of(load_keras(json_path, hdf5_path, by_name=by_name))

    @staticmethod
    def train(output, data, label, opt_method, criterion, batch_size,
              end_when, session=None, bigdl_type="float"):
        raise NotImplementedError(
            "Model.train (TF-graph training): use bigdl_tpu.interop."
            "tf_session.Session.train")

    def stop_gradient(self, stop_layers, bigdl_type="float"):
        """Cut backprop at the named layers (reference
        Graph.stopGradient): neither they nor anything upstream of them
        receives gradients."""
        self.value.stop_gradient(stop_layers)
        return self

    def node(self, name, bigdl_type="float"):
        for n in self.value.exec_order:
            if getattr(n.module, "name", None) == name:
                return Node.of(n)
        raise KeyError(name)

    def save_graph_topology(self, log_path, bigdl_type="float"):
        """Write the model DAG as a TensorBoard graph event (reference
        Graph.saveGraphTopology)."""
        from bigdl_tpu.visualization import save_graph_topology
        save_graph_topology(self.value, log_path)
        return self


# ---------------------------------------------------------------------------
# Explicit signatures: layers whose pyspark arg lists interleave
# regularizers / init tensors / propagate_back with structural args, so a
# positional passthrough would mis-bind.
# ---------------------------------------------------------------------------

def _set_initial_weights(module, mapping):
    """Install explicit init ndarrays (reference init_weight/init_bias)
    after transposing reference layouts into native ones."""
    import jax
    import jax.numpy as jnp
    module.ensure_params()
    params = dict(module.parameters())
    for key, array in mapping.items():
        if array is None:
            continue
        tgt = params[key]
        arr = jnp.asarray(np.asarray(array), jnp.asarray(tgt).dtype)
        if arr.shape != jnp.asarray(tgt).shape:
            raise ValueError(
                f"init {key}: shape {arr.shape} vs expected "
                f"{jnp.asarray(tgt).shape}")
        params[key] = arr
    module.set_params(params)


def _linear_weight_to_native(w):
    """Reference Linear weight (out, in) -> native (in, out)."""
    if w is None:
        return None
    return np.asarray(w).T


def _conv_weight_to_native(w, n_group=1):
    """Reference conv weight (group, out/group, in/group, kh, kw) or
    (out, in, kh, kw) -> native HWIO (kh, kw, in/group, out)."""
    if w is None:
        return None
    w = np.asarray(w)
    if w.ndim == 5:
        g, og, i, kh, kw = w.shape
        w = w.reshape(g * og, i, kh, kw)
    return np.transpose(w, (2, 3, 1, 0))


class Linear(Layer):
    """Reference pyspark/bigdl/nn/layer.py:905."""

    def __init__(self, input_size, output_size, with_bias=True,
                 wRegularizer=None, bRegularizer=None, init_weight=None,
                 init_bias=None, init_grad_weight=None, init_grad_bias=None,
                 bigdl_type="float"):
        m = _nn.Linear(input_size, output_size, with_bias=with_bias)
        super().__init__(m, bigdl_type)
        _attach_regularizers(m, wRegularizer, bRegularizer)
        if init_weight is not None or init_bias is not None:
            _set_initial_weights(m, {
                "weight": _linear_weight_to_native(init_weight),
                "bias": init_bias})


class SpatialConvolution(Layer):
    """Reference pyspark/bigdl/nn/layer.py:1373. NCHW default."""

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0, n_group=1,
                 propagate_back=True, wRegularizer=None, bRegularizer=None,
                 init_weight=None, init_bias=None, init_grad_weight=None,
                 init_grad_bias=None, with_bias=True, data_format="NCHW",
                 bigdl_type="float"):
        m = _nn.SpatialConvolution(
            n_input_plane, n_output_plane, kernel_w, kernel_h, stride_w,
            stride_h, pad_w=pad_w, pad_h=pad_h, n_group=n_group,
            with_bias=with_bias, data_format=data_format)
        super().__init__(m, bigdl_type)
        _attach_regularizers(m, wRegularizer, bRegularizer)
        if init_weight is not None or init_bias is not None:
            _set_initial_weights(m, {
                "weight": _conv_weight_to_native(init_weight, n_group),
                "bias": init_bias})


class SpatialMaxPooling(Layer):
    """Reference pyspark/bigdl/nn/layer.py:1489. NCHW default."""

    def __init__(self, kw, kh, dw=1, dh=1, pad_w=0, pad_h=0,
                 to_ceil=False, format="NCHW", bigdl_type="float"):
        super().__init__(_nn.SpatialMaxPooling(
            kw, kh, dw, dh, pad_w=pad_w, pad_h=pad_h, ceil_mode=to_ceil,
            data_format=format), bigdl_type)


class SpatialAveragePooling(Layer):
    """Reference pyspark SpatialAveragePooling. NCHW default."""

    def __init__(self, kw, kh, dw=1, dh=1, pad_w=0, pad_h=0,
                 global_pooling=False, ceil_mode=False,
                 count_include_pad=True, divide=True, format="NCHW",
                 bigdl_type="float"):
        if global_pooling:
            raise NotImplementedError(
                "global_pooling=True: size the kernel to the feature map "
                "(reference semantics) or use bigdl_tpu pooling directly")
        super().__init__(_nn.SpatialAveragePooling(
            kw, kh, dw, dh, pad_w=pad_w, pad_h=pad_h, ceil_mode=ceil_mode,
            count_include_pad=count_include_pad, divide=divide,
            data_format=format), bigdl_type)


class SpatialBatchNormalization(Layer):
    """Reference pyspark SpatialBatchNormalization. NCHW input."""

    def __init__(self, n_output, eps=1e-5, momentum=0.1, affine=True,
                 init_weight=None, init_bias=None, init_grad_weight=None,
                 init_grad_bias=None, data_format="NCHW",
                 bigdl_type="float"):
        m = _nn.SpatialBatchNormalization(
            n_output, eps=eps, momentum=momentum, affine=affine,
            data_format=data_format)
        super().__init__(m, bigdl_type)
        if affine and (init_weight is not None or init_bias is not None):
            _set_initial_weights(m, {"weight": init_weight,
                                     "bias": init_bias})


class BatchNormalization(Layer):
    """Reference pyspark BatchNormalization (1-D features)."""

    def __init__(self, n_output, eps=1e-5, momentum=0.1, affine=True,
                 init_weight=None, init_bias=None, init_grad_weight=None,
                 init_grad_bias=None, bigdl_type="float"):
        m = _nn.BatchNormalization(n_output, eps=eps, momentum=momentum,
                                   affine=affine)
        super().__init__(m, bigdl_type)
        if affine and (init_weight is not None or init_bias is not None):
            _set_initial_weights(m, {"weight": init_weight,
                                     "bias": init_bias})


class LookupTable(Layer):
    """Reference pyspark LookupTable."""

    def __init__(self, n_index, n_output, padding_value=0.0, max_norm=1e20,
                 norm_type=2.0, should_scale_grad_by_freq=False,
                 wRegularizer=None, bigdl_type="float"):
        m = _nn.LookupTable(n_index, n_output, padding_value=padding_value,
                            max_norm=max_norm, norm_type=norm_type)
        super().__init__(m, bigdl_type)
        _attach_regularizers(m, wRegularizer, None)


class Dropout(Layer):
    """Reference pyspark Dropout."""

    def __init__(self, init_p=0.5, inplace=False, scale=True,
                 bigdl_type="float"):
        super().__init__(_nn.Dropout(init_p, inplace=inplace, scale=scale),
                         bigdl_type)


class Reshape(Layer):
    """Reference pyspark Reshape."""

    def __init__(self, size, batch_mode=None, bigdl_type="float"):
        super().__init__(_nn.Reshape(list(size), batch_mode=batch_mode
                                     if batch_mode is not None else True),
                         bigdl_type)


class View(Layer):
    def __init__(self, sizes, num_input_dims=0, bigdl_type="float"):
        # num_input_dims only disambiguates batch handling in the
        # reference; the native View already infers batch mode
        super().__init__(_nn.View(to_list(sizes)), bigdl_type)


class Echo(Layer):
    def __init__(self, bigdl_type="float"):
        super().__init__(_nn.Echo(), bigdl_type)


class TemporalConvolution(Layer):
    """Reference pyspark TemporalConvolution."""

    def __init__(self, input_frame_size, output_frame_size, kernel_w,
                 stride_w=1, propagate_back=True, weight_regularizer=None,
                 bias_regularizer=None, init_weight=None, init_bias=None,
                 init_grad_weight=None, init_grad_bias=None,
                 bigdl_type="float"):
        m = _nn.TemporalConvolution(input_frame_size, output_frame_size,
                                    kernel_w, stride_w)
        super().__init__(m, bigdl_type)
        _attach_regularizers(m, weight_regularizer, bias_regularizer)
        if init_weight is not None or init_bias is not None:
            _set_initial_weights(m, {"weight": init_weight,
                                     "bias": init_bias})


class Input(Node):
    """Reference pyspark/bigdl/nn/layer.py:2694 — note the reference's own
    caveat: "the return is not a layer but a Node containing input layer".
    Wraps the native `InputNode()`."""

    def __init__(self, name=None, bigdl_type="float"):
        super().__init__(_nn.InputNode(name), bigdl_type)


class L1Penalty(Layer):
    """Reference pyspark L1Penalty — an identity layer that adds an L1
    activity penalty to the loss. Native analogue: ActivityRegularization
    (the reference class lives in layer.py; the native one carries the
    penalty through the functional loss context)."""

    def __init__(self, l1weight, size_average=False, provide_output=True,
                 bigdl_type="float"):
        super().__init__(_nn.ActivityRegularization(l1=float(l1weight)),
                         bigdl_type)


def _attach_regularizers(module, w_reg, b_reg):
    """Per-layer regularizers (reference wRegularizer/bRegularizer).
    Compat objects wrap bigdl_tpu regularizers in `.value`."""
    if w_reg is not None:
        module.w_regularizer = getattr(w_reg, "value", w_reg)
    if b_reg is not None:
        module.b_regularizer = getattr(b_reg, "value", b_reg)


# ---------------------------------------------------------------------------
# Generated passthroughs: every other reference pyspark layer class whose
# bigdl_tpu constructor uses the same (snake_case) parameter names — both
# APIs were derived from the same Scala createX factories, so keyword and
# prefix-positional calls bind identically. `bigdl_type` is stripped.
# ---------------------------------------------------------------------------

def _unwrap(v):
    """Compat Layer/Node args -> the underlying bigdl_tpu object, so
    passthroughs accept wrapped submodules (e.g. TimeDistributed(layer))."""
    if isinstance(v, (Layer, Node)):
        return v.value
    if isinstance(v, (list, tuple)):
        return type(v)(_unwrap(x) for x in v)
    return v


def _passthrough(cls_name):
    tpu_cls = getattr(_nn, cls_name)
    # native containers (Concat, Recurrent, ParallelTable, ...) surface
    # the reference's .add()/.layers through the compat Container base
    from bigdl_tpu.nn.containers import Container as _TpuContainer
    base = Container if issubclass(tpu_cls, _TpuContainer) else Layer

    def __init__(self, *args, bigdl_type="float", **kwargs):
        kwargs.pop("bigdl_type", None)
        args = tuple(_unwrap(a) for a in args)
        kwargs = {k: _unwrap(v) for k, v in kwargs.items()}
        Layer.__init__(self, tpu_cls(*args, **kwargs), bigdl_type)

    doc = (f"pyspark-compat passthrough for bigdl_tpu.nn.{cls_name} "
           f"(reference pyspark/bigdl/nn/layer.py create{cls_name}).")
    return type(cls_name, (base,), {"__init__": __init__, "__doc__": doc})


_EXPLICIT = {
    "Layer", "Container", "Model", "Sequential", "Concat", "Recurrent",
    "Squeeze", "Select", "Node", "Linear",
    "SpatialConvolution", "SpatialMaxPooling", "SpatialAveragePooling",
    "SpatialBatchNormalization", "BatchNormalization", "LookupTable",
    "Dropout", "Reshape", "View", "Echo", "TemporalConvolution",
    "L1Penalty", "Input",
}

_module = sys.modules[__name__]
for _name in dir(_nn):
    if _name.startswith("_") or _name in _EXPLICIT:
        continue
    _obj = getattr(_nn, _name)
    if isinstance(_obj, type) and issubclass(_obj, _nn.Module) and \
            not getattr(_obj, "_is_criterion", False):
        setattr(_module, _name, _passthrough(_name))
        __all__.append(_name)

__all__ += sorted(_EXPLICIT - {"Layer", "Container", "Model", "Sequential",
                               "Node"})
