"""pyspark-BigDL API compatibility: `bigdl.nn.criterion`.

Parity: reference pyspark/bigdl/nn/criterion.py — every class there
forwards to a JVM createX factory; here each wraps the same-named
`bigdl_tpu.nn` criterion (built from the same Scala surface, same
snake_case arg names) in `.value`.

`forward`/`backward` mirror the reference's debug-only single-shot
evaluation (criterion.py:42-75): ndarray in, float / ndarray out, with
the backward computed by autodiff instead of a hand-written gradient.
"""

from __future__ import annotations

import sys

import numpy as np

import bigdl_tpu.nn as _nn
from bigdl_tpu.nn.criterion import Criterion as _TpuCriterion
from bigdl.util.common import JTensor, to_list


def _jnp(x):
    import jax.numpy as jnp
    if isinstance(x, JTensor):
        x = x.to_ndarray()
    return jnp.asarray(np.asarray(x))


class Criterion(object):
    """Reference pyspark/bigdl/nn/criterion.py:31."""

    def __init__(self, jvalue, bigdl_type="float", *args):
        if jvalue is None:
            raise ValueError(
                f"{type(self).__name__}: compat criterions must pass the "
                "constructed bigdl_tpu criterion as jvalue")
        self.value = jvalue
        self.bigdl_type = bigdl_type

    @classmethod
    def of(cls, jcriterion, bigdl_type="float"):
        criterion = Criterion(jcriterion, bigdl_type)
        return criterion

    def forward(self, input, target):
        ins = [_jnp(i) for i in to_list(input)]
        tgt = [_jnp(t) for t in to_list(target)]
        out = self.value.forward(ins[0] if len(ins) == 1 else ins,
                                 tgt[0] if len(tgt) == 1 else tgt)
        return float(out)

    def backward(self, input, target):
        import jax
        ins = [_jnp(i) for i in to_list(input)]
        tgt = [_jnp(t) for t in to_list(target)]
        x = ins[0] if len(ins) == 1 else ins
        t = tgt[0] if len(tgt) == 1 else tgt
        grad = jax.grad(lambda xx: self.value.forward(xx, t))(x)
        if isinstance(grad, (list, tuple)):
            return [np.asarray(g) for g in grad]
        return np.asarray(grad)

    def __str__(self):
        return str(self.value)


def _passthrough(cls_name):
    tpu_cls = getattr(_nn, cls_name)

    def _unwrap(v):
        if isinstance(v, Criterion):
            return v.value
        if isinstance(v, (list, tuple)):
            return type(v)(_unwrap(x) for x in v)
        if isinstance(v, JTensor):
            return v.to_ndarray()
        return v

    def __init__(self, *args, bigdl_type="float", **kwargs):
        kwargs.pop("bigdl_type", None)
        args = tuple(_unwrap(a) for a in args)
        kwargs = {k: _unwrap(v) for k, v in kwargs.items()}
        Criterion.__init__(self, tpu_cls(*args, **kwargs), bigdl_type)

    doc = (f"pyspark-compat passthrough for bigdl_tpu.nn.{cls_name} "
           f"(reference pyspark/bigdl/nn/criterion.py {cls_name}).")
    cls = type(cls_name, (Criterion,), {"__init__": __init__,
                                        "__doc__": doc})
    # MultiCriterion/ParallelCriterion compose via add() in the reference
    if hasattr(tpu_cls, "add"):
        def add(self, criterion, weight=1.0):
            self.value.add(getattr(criterion, "value", criterion), weight)
            return self
        cls.add = add
    return cls


__all__ = ["Criterion"]
_module = sys.modules[__name__]
for _name in dir(_nn):
    _obj = getattr(_nn, _name)
    if isinstance(_obj, type) and issubclass(_obj, _TpuCriterion) and \
            _obj is not _TpuCriterion:
        setattr(_module, _name, _passthrough(_name))
        __all__.append(_name)
