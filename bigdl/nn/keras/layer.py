"""pyspark-BigDL API compatibility: `bigdl.nn.keras.layer`.

Parity: reference pyspark/bigdl/nn/keras/layer.py — the Keras-1.2.2-
style layer classes. Every class delegates to the same-named
`bigdl_tpu.keras` layer (both surfaces were derived from the same Scala
keras package, same constructor arg names), wrapped so `.value` holds
the native layer, matching the rest of the compat namespace.
"""

from __future__ import annotations

import sys

import bigdl_tpu.keras as _keras
from bigdl_tpu.keras import KerasLayer as _TpuKerasLayer


class KerasLayer:
    """Base wrapper (reference keras/layer.py KerasLayer)."""

    def __init__(self, tpu_layer, bigdl_type="float"):
        self.value = tpu_layer
        self.bigdl_type = bigdl_type

    def set_name(self, name):
        self.value.name = name
        return self

    def name(self):
        return self.value.name

    def __call__(self, x=None):
        from bigdl.util.common import to_list
        xs = [getattr(i, "value", i) for i in to_list(x)] if x is not None \
            else []
        out = self.value(xs[0] if len(xs) == 1 else xs)
        return _Node(out)


class _Node:
    def __init__(self, tpu_node):
        self.value = tpu_node


def _passthrough(cls_name):
    tpu_cls = getattr(_keras, cls_name)

    def _unwrap(v):
        if isinstance(v, (KerasLayer, _Node)):
            return v.value
        if isinstance(v, (list, tuple)):
            return type(v)(_unwrap(x) for x in v)
        return v

    def __init__(self, *args, bigdl_type="float", **kwargs):
        kwargs.pop("bigdl_type", None)
        args = tuple(_unwrap(a) for a in args)
        kwargs = {k: _unwrap(v) for k, v in kwargs.items()}
        KerasLayer.__init__(self, tpu_cls(*args, **kwargs), bigdl_type)

    doc = (f"pyspark-compat passthrough for bigdl_tpu.keras.{cls_name} "
           f"(reference pyspark/bigdl/nn/keras/layer.py {cls_name}).")
    return type(cls_name, (KerasLayer,), {"__init__": __init__,
                                          "__doc__": doc})


__all__ = ["KerasLayer"]
_module = sys.modules[__name__]
for _name in dir(_keras):
    if _name.startswith("_") or _name in ("KerasLayer", "KerasModel",
                                          "Sequential", "Model"):
        continue
    _obj = getattr(_keras, _name)
    if isinstance(_obj, type) and issubclass(_obj, _TpuKerasLayer):
        setattr(_module, _name, _passthrough(_name))
        __all__.append(_name)
