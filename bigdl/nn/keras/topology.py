"""pyspark-BigDL API compatibility: `bigdl.nn.keras.topology`.

Parity: reference pyspark/bigdl/nn/keras/topology.py — the Keras-style
Sequential/Model containers. Delegates to `bigdl_tpu.keras`, which
carries the full Keras-1.2.2-style surface (compile/fit/evaluate/
predict) natively; data is lists/ndarrays instead of RDDs.
"""

from __future__ import annotations

import bigdl_tpu.keras as _keras


class KerasModelWrapper:
    def __init__(self, tpu_model, bigdl_type="float"):
        self.value = tpu_model
        self.bigdl_type = bigdl_type

    def __getattr__(self, name):
        return getattr(self.value, name)


class Sequential(KerasModelWrapper):
    """Reference topology.py Sequential."""

    def __init__(self, name=None, bigdl_type="float"):
        super().__init__(_keras.Sequential(name=name) if name
                         else _keras.Sequential(), bigdl_type)

    def add(self, layer):
        self.value.add(getattr(layer, "value", layer))
        return self


class Model(KerasModelWrapper):
    """Reference topology.py Model (graph-style)."""

    def __init__(self, input, output, name=None, bigdl_type="float"):
        from bigdl.util.common import to_list
        ins = [getattr(i, "value", i) for i in to_list(input)]
        outs = [getattr(o, "value", o) for o in to_list(output)]
        super().__init__(_keras.Model(ins, outs), bigdl_type)
