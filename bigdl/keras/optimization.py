"""pyspark-BigDL API compatibility: `bigdl.keras.optimization`.

Parity: reference pyspark/bigdl/keras/optimization.py — OptimConverter
maps Keras losses / optimizers / metrics onto BigDL counterparts. The
loss table matches the reference's; optimizer objects are read via
duck-typed attrs (lr/decay/momentum/...) so both Keras-1 objects and
plain namespaces convert.
"""

from __future__ import annotations

import warnings

import bigdl.nn.criterion as bcriterion
import bigdl.optim.optimizer as boptimizer
from bigdl.util.common import to_list


def _num(v):
    """Read a keras hyperparameter: plain number or backend variable."""
    try:
        return float(v)
    except TypeError:
        pass
    try:
        from keras import backend as K
        return float(K.eval(v))
    except Exception:
        return float(getattr(v, "value", lambda: v)())


class OptimConverter:

    @staticmethod
    def to_bigdl_metrics(metrics):
        bmetrics = []
        for metric in to_list(metrics):
            if metric == "accuracy":
                bmetrics.append(boptimizer.Top1Accuracy())
            else:
                raise Exception("Unsupported metric: %s" % metric)
        return bmetrics

    _LOSSES = {
        "categorical_crossentropy": lambda: bcriterion.CategoricalCrossEntropy(),
        "mse": lambda: bcriterion.MSECriterion(),
        "mean_squared_error": lambda: bcriterion.MSECriterion(),
        "binary_crossentropy": lambda: bcriterion.BCECriterion(),
        "mae": lambda: bcriterion.AbsCriterion(),
        "mean_absolute_error": lambda: bcriterion.AbsCriterion(),
        "hinge": lambda: bcriterion.MarginCriterion(),
        "squared_hinge": lambda: bcriterion.MarginCriterion(squared=True),
        "mean_absolute_percentage_error":
            lambda: bcriterion.MeanAbsolutePercentageCriterion(),
        "mape": lambda: bcriterion.MeanAbsolutePercentageCriterion(),
        "mean_squared_logarithmic_error":
            lambda: bcriterion.MeanSquaredLogarithmicCriterion(),
        "msle": lambda: bcriterion.MeanSquaredLogarithmicCriterion(),
        "sparse_categorical_crossentropy":
            lambda: bcriterion.ClassNLLCriterion(logProbAsInput=False),
        "kullback_leibler_divergence":
            lambda: bcriterion.KullbackLeiblerDivergenceCriterion(),
        "kld": lambda: bcriterion.KullbackLeiblerDivergenceCriterion(),
        "poisson": lambda: bcriterion.PoissonCriterion(),
        "cosine_proximity": lambda: bcriterion.CosineProximityCriterion(),
        "cosine": lambda: bcriterion.CosineProximityCriterion(),
    }

    @staticmethod
    def to_bigdl_criterion(kloss):
        name = kloss if isinstance(kloss, str) else \
            getattr(kloss, "__name__", str(kloss))
        make = OptimConverter._LOSSES.get(name.lower())
        if make is None:
            raise Exception("Not supported loss: %s" % kloss)
        return make()

    @staticmethod
    def to_bigdl_optim_method(koptim_method):
        cls = type(koptim_method).__name__
        lr = _num(getattr(koptim_method, "lr", 0.01))
        decay = _num(getattr(koptim_method, "decay", 0.0))
        if cls == "Adagrad":
            warnings.warn("For Adagrad, we don't support epsilon for now")
            return boptimizer.Adagrad(learningrate=lr,
                                      learningrate_decay=decay)
        if cls == "SGD":
            return boptimizer.SGD(
                learningrate=lr, learningrate_decay=decay,
                momentum=_num(getattr(koptim_method, "momentum", 0.0)),
                nesterov=bool(getattr(koptim_method, "nesterov", False)))
        if cls == "Adam":
            return boptimizer.Adam(
                learningrate=lr, learningrate_decay=decay,
                beta1=_num(getattr(koptim_method, "beta_1", 0.9)),
                beta2=_num(getattr(koptim_method, "beta_2", 0.999)),
                epsilon=_num(getattr(koptim_method, "epsilon", 1e-8)))
        if cls == "RMSprop":
            return boptimizer.RMSprop(
                learningrate=lr,
                decayrate=_num(getattr(koptim_method, "rho", 0.9)),
                epsilon=_num(getattr(koptim_method, "epsilon", 1e-8)))
        if cls == "Adadelta":
            warnings.warn("For Adadelta, we don't support learning rate "
                          "and learning rate decay for now")
            return boptimizer.Adadelta(
                decayrate=_num(getattr(koptim_method, "rho", 0.95)),
                epsilon=_num(getattr(koptim_method, "epsilon", 1e-8)))
        if cls == "Adamax":
            warnings.warn("For Adamax, we don't support learning rate "
                          "decay for now")
            return boptimizer.Adamax(
                learningrate=lr,
                beta1=_num(getattr(koptim_method, "beta_1", 0.9)),
                beta2=_num(getattr(koptim_method, "beta_2", 0.999)),
                epsilon=_num(getattr(koptim_method, "epsilon", 1e-8)))
        raise Exception("Not supported optimizer: %s" % cls)
