"""pyspark-BigDL API compatibility: `bigdl.keras.ToBigDLHelper`.

Parity: reference pyspark/bigdl/keras/ToBigDLHelper.py — small
Keras->BigDL translation helpers: dim-ordering strings, border-mode ->
padding, init-method and regularizer mapping.
"""

from __future__ import annotations

import bigdl.nn.initialization_method as BInit
from bigdl.optim.optimizer import L1L2Regularizer as BRegularizer


def to_bigdl_2d_ordering(order):
    if order == "tf":
        return "NHWC"
    if order == "th":
        return "NCHW"
    raise Exception("Unsupported dim_ordering: %s" % order)


def to_bigdl_3d_ordering(order):
    if order == "tf":
        return "channel_last"
    if order == "th":
        return "channel_first"
    raise Exception("Unsupported dim_ordering: %s" % order)


def to_bigdl_3d_padding(border_mode):
    if border_mode == "valid":
        return 0, 0
    if border_mode == "same":
        return -1, -1  # sentinel: compute SAME padding in the layer
    raise Exception("Unsupported border mode: %s" % border_mode)


def to_bigdl_2d_padding(border_mode, *args):
    if border_mode == "same":
        return -1, -1  # BigDL's SAME sentinel
    if border_mode == "valid":
        return 0, 0
    raise Exception("Unsupported border mode: %s" % border_mode)


def to_bigdl_init(kinit_method):
    if kinit_method == "glorot_uniform":
        return BInit.Xavier()
    if kinit_method == "one":
        return BInit.Ones()
    if kinit_method == "zero":
        return BInit.Zeros()
    if kinit_method == "uniform":
        return BInit.RandomUniform(lower=-0.05, upper=0.05)
    if kinit_method == "normal":
        return BInit.RandomNormal(mean=0.0, stdv=0.05)
    raise Exception("Unsupported init type: %s" % kinit_method)


def to_bigdl_reg(reg):
    if reg:
        return BRegularizer(reg.get('l1', 0.0), reg.get('l2', 0.0))
    return None
