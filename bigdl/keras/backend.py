"""pyspark-BigDL API compatibility: `bigdl.keras.backend`.

Parity: reference pyspark/bigdl/keras/backend.py — `KerasModelWrapper` /
`with_bigdl_backend`: take a compiled Keras-1.2.2 model object and run
its fit/predict/evaluate on the BigDL stack. Declared delta: the
reference's distributed mode consumes RDD[Sample]; this runtime is
Spark-free, so ndarray (local-mode) inputs are the supported path and
`is_distributed=True` raises with that explanation.
"""

from __future__ import annotations

import numpy as np

from bigdl.keras.converter import DefinitionLoader, WeightLoader
from bigdl.keras.optimization import OptimConverter
from bigdl.util.common import (init_engine, redire_spark_logs,
                               show_bigdl_info_logs)


def _no_rdd(flag):
    if flag:
        raise Exception(
            "is_distributed=True needs Spark RDDs; this build is "
            "Spark-free — pass ndarrays (local mode)")


class KerasModelWrapper:

    def __init__(self, kmodel):
        redire_spark_logs()
        show_bigdl_info_logs()
        init_engine()
        self.bmodel = DefinitionLoader.from_kmodel(kmodel)
        WeightLoader.load_weights_from_kmodel(self.bmodel, kmodel)
        kloss = getattr(kmodel, "loss", None)
        self.criterion = OptimConverter.to_bigdl_criterion(kloss) \
            if kloss else None
        kopt = getattr(kmodel, "optimizer", None)
        self.optim_method = OptimConverter.to_bigdl_optim_method(kopt) \
            if kopt else None
        kmetrics = getattr(kmodel, "metrics", None)
        self.metrics = OptimConverter.to_bigdl_metrics(kmetrics) \
            if kmetrics else None

    def predict(self, x, batch_size=None, verbose=None,
                is_distributed=False):
        _no_rdd(is_distributed)
        if not isinstance(x, (np.ndarray, list)):
            raise Exception("not supported type: %s" % type(x).__name__)
        return self.bmodel.predict_local(x)

    def evaluate(self, x, y, batch_size=32, sample_weight=None,
                 is_distributed=False):
        if sample_weight is not None:
            raise Exception("unsupported: sample_weight")
        _no_rdd(is_distributed)
        if not self.metrics:
            raise Exception("No Metrics found.")
        return self._evaluate_local(x, y, batch_size)

    def _evaluate_local(self, x, y, batch_size):
        from bigdl_tpu.dataset.dataset import DataSet
        res = self.bmodel.value.evaluate_on(
            DataSet.from_arrays(np.asarray(x), np.asarray(y)),
            [m.value if hasattr(m, "value") else m for m in self.metrics],
            batch_size=batch_size)
        return [r.result()[0] for r in res]

    def fit(self, x, y=None, batch_size=32, nb_epoch=10, verbose=1,
            callbacks=None, validation_split=0., validation_data=None,
            shuffle=True, class_weight=None, sample_weight=None,
            initial_epoch=0, is_distributed=False):
        if callbacks:
            raise Exception("We don't support callbacks in fit for now")
        if class_weight or sample_weight or initial_epoch or \
                validation_split:
            raise Exception(
                "unsupported fit arguments: class_weight / sample_weight "
                "/ initial_epoch / validation_split")
        _no_rdd(is_distributed)
        from bigdl.optim.optimizer import (EveryEpoch, MaxEpoch, Optimizer,
                                           SGD)
        optimizer = Optimizer.create(
            model=self.bmodel,
            training_set=(np.asarray(x), np.asarray(y)),
            criterion=self.criterion,
            optim_method=self.optim_method or SGD(),
            end_trigger=MaxEpoch(nb_epoch),
            batch_size=batch_size)
        if validation_data is not None and self.metrics:
            vx, vy = validation_data
            optimizer.set_validation(
                batch_size=batch_size, X_val=np.asarray(vx),
                Y_val=np.asarray(vy), trigger=EveryEpoch(),
                val_method=self.metrics)
        optimizer.optimize()
        return self


def with_bigdl_backend(kmodel):
    """Compile-and-swap: returns a wrapper whose fit/evaluate/predict run
    on this framework (reference with_bigdl_backend)."""
    return KerasModelWrapper(kmodel)
