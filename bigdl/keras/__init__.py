"""pyspark-BigDL API compatibility: `bigdl.keras`.

Parity: reference pyspark/bigdl/keras — the Keras-1.2.2 model converter
namespace (DefinitionLoader/WeightLoader in converter.py, the
keras-object training facade in backend.py, loss/optimizer mapping in
optimization.py, and the small translation helpers in ToBigDLHelper.py).
The conversion machinery itself lives in
bigdl_tpu/interop/keras_converter.py; this package is the reference
import surface over it.
"""
