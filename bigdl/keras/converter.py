"""pyspark-BigDL API compatibility: `bigdl.keras.converter`.

Parity: reference pyspark/bigdl/keras/converter.py (2,167-LoC package
entry) — `DefinitionLoader` builds a BigDL model from Keras json /
in-memory kmodel, `WeightLoader` installs hdf5 / kmodel weights. The
actual conversion (layer mapping, gate reordering, dim-ordering kernel
transposes) is bigdl_tpu/interop/keras_converter.py, torch-oracled in
tests/test_interop.py; these classes adapt it to the reference's
classmethod surface and return compat `Layer` facades.
"""

from __future__ import annotations

import json
import tempfile

from bigdl.nn.layer import Layer

from bigdl_tpu.interop import keras_converter as _kc


def _wrap(model):
    return Layer.of(model)


class DefinitionLoader:

    @classmethod
    def from_json_path(cls, json_path):
        with open(json_path) as f:
            return cls.from_json_str(f.read())

    @classmethod
    def from_json_str(cls, json_str):
        return _wrap(_kc.DefinitionLoader.from_config(json.loads(json_str)))

    @classmethod
    def from_kmodel(cls, kmodel):
        """Build from a live keras model object (reference from_kmodel
        serializes it to json first; same here)."""
        return cls.from_json_str(kmodel.to_json())


class WeightLoader:

    @staticmethod
    def load_weights_from_hdf5(bmodel, def_json, weights_hdf5,
                               by_name=False):
        """Load trained weights from `weights_hdf5` into `bmodel` (built
        from `def_json`). `by_name` is accepted for parity; matching is
        by layer name already (the hdf5 layout keys on names)."""
        with open(def_json) as f:
            th = _kc._detect_th(json.loads(f.read()))
        value = getattr(bmodel, "value", bmodel)
        _kc.WeightLoader.load_weights(value, weights_hdf5, th=th)
        return bmodel

    @staticmethod
    def load_weights_from_json_hdf5(def_json, weights_hdf5, by_name=False):
        """(reference entry) build from json AND install hdf5 weights."""
        return _wrap(_kc.load_keras(def_json, weights_hdf5))

    @staticmethod
    def load_weights_from_kmodel(bmodel, kmodel):
        """Install a live kmodel's current weights into `bmodel`."""
        with tempfile.NamedTemporaryFile(suffix=".h5") as f:
            kmodel.save_weights(f.name, overwrite=True)
            th = _kc._detect_th(json.loads(kmodel.to_json()))
            value = getattr(bmodel, "value", bmodel)
            _kc.WeightLoader.load_weights(value, f.name, th=th)
        return bmodel
