"""pyspark-BigDL API compatibility: `bigdl.transform.vision.image`.

Parity: reference pyspark/bigdl/transform/vision/image.py — the
ImageFrame/FeatureTransformer vision pipeline. Delegates to
`bigdl_tpu.transform.vision`, which carries the full reference
augmentation set natively; `DistributedImageFrame` folds into the local
one (the RDD -> local swap, like everywhere in the compat namespace).
"""

from __future__ import annotations

import sys

import bigdl_tpu.transform.vision as _V


class FeatureTransformer:
    """Reference image.py:27 — base wrapper; `.value` holds the native
    transformer. `transform` applies to one ImageFeature; calling the
    object applies to an ImageFrame."""

    def __init__(self, tpu_transformer, bigdl_type="float"):
        self.value = tpu_transformer
        self.bigdl_type = bigdl_type

    def transform(self, image_feature, bigdl_type="float"):
        native = self.value(getattr(image_feature, "value", image_feature))
        if isinstance(image_feature, ImageFeature):
            # reference transform mutates and returns the SAME wrapper
            # (reference image.py:36-41)
            image_feature.value = native
            return image_feature
        return native

    def __call__(self, image_frame, bigdl_type="float"):
        return ImageFrame.of(
            _unwrap(image_frame).transform(self.value))


def _unwrap(v):
    return getattr(v, "value", v)


def _img(f):
    """ImageFeature.image is a method on raw features and a plain array
    once MatToTensor/transforms have materialized it."""
    im = f.image
    return im() if callable(im) else im


def _lbl(f):
    lb = f.label
    return lb() if callable(lb) else lb


class Pipeline(FeatureTransformer):
    """Reference image.py:51 — chained transformers."""

    def __init__(self, transformers, bigdl_type="float"):
        from bigdl_tpu.dataset import chain
        super().__init__(chain(*[_unwrap(t) for t in transformers]),
                         bigdl_type)


class ImageFeature:
    """Reference image.py:62 — one image + metadata."""

    def __init__(self, image=None, label=None, path=None,
                 bigdl_type="float"):
        self.value = _V.ImageFeature(image, label=label, uri=path)
        self.bigdl_type = bigdl_type

    def get_image(self, float_key="floats", to_chw=True):
        import numpy as np
        img = _img(self.value)
        if to_chw and img.ndim == 3:
            img = np.transpose(img, (2, 0, 1))
        return img

    def get_label(self):
        return _lbl(self.value)

    def keys(self):
        return self.value.keys()


class ImageFrame:
    """Reference image.py:100 — a collection of ImageFeatures."""

    def __init__(self, jvalue, bigdl_type="float"):
        self.value = jvalue
        self.bigdl_type = bigdl_type

    @classmethod
    def of(cls, native):
        return cls(native)

    @classmethod
    def read(cls, path, sc=None, min_partitions=1, bigdl_type="float"):
        """Read images from a local path or glob (the reference's
        HDFS/RDD read folds into the local frame)."""
        return cls(_V.ImageFrame.read(path))

    def transform(self, transformer, bigdl_type="float"):
        return ImageFrame.of(self.value.transform(_unwrap(transformer)))

    def get_image(self, float_key="floats", to_chw=True):
        import numpy as np
        imgs = [_img(f) for f in self.value.features]
        if to_chw:
            imgs = [np.transpose(i, (2, 0, 1)) if i.ndim == 3 else i
                    for i in imgs]
        return imgs

    def get_label(self):
        return [_lbl(f) for f in self.value.features]

    def is_local(self):
        return True

    def is_distributed(self):
        return False


class LocalImageFrame(ImageFrame):
    """Reference image.py:209 — built from a list of images (+labels)."""

    def __init__(self, image_list, label_list=None, bigdl_type="float"):
        feats = []
        for i, img in enumerate(image_list):
            label = label_list[i] if label_list is not None else None
            feats.append(_V.ImageFeature(img, label=label))
        super().__init__(_V.LocalImageFrame(feats), bigdl_type)


class DistributedImageFrame(ImageFrame):
    """Reference image.py:257 — RDD-backed; here the declared swap makes
    it the local frame over a plain list."""

    def __init__(self, image_rdd, label_rdd=None, bigdl_type="float"):
        images = list(image_rdd)
        labels = list(label_rdd) if label_rdd is not None else None
        frame = LocalImageFrame(images, labels, bigdl_type)
        super().__init__(frame.value, bigdl_type)


def _passthrough(cls_name):
    """STRICT passthrough: reference args that do not exist on the native
    class raise instead of silently landing in trailing params (e.g. the
    native rng `seed`) — a mis-bound augmentation corrupts data with no
    error, the worst failure mode a compat layer can have."""
    import inspect as _inspect
    tpu_cls = getattr(_V, cls_name)
    _params = [p.name for p in
               list(_inspect.signature(tpu_cls.__init__)
                    .parameters.values())[1:] if p.name != "seed"]

    def __init__(self, *args, bigdl_type="float", **kwargs):
        if len(args) > len(_params) or set(kwargs) - set(_params):
            raise TypeError(
                f"{cls_name}: arguments beyond the native surface "
                f"{_params} are not silently absorbed — see "
                f"bigdl_tpu.transform.vision.{cls_name} for the "
                f"supported parameters")
        FeatureTransformer.__init__(self, tpu_cls(*args, **kwargs),
                                    bigdl_type)

    doc = (f"pyspark-compat passthrough for bigdl_tpu.transform.vision."
           f"{cls_name} (reference pyspark/bigdl/transform/vision/"
           f"image.py {cls_name}); strict about unsupported args.")
    return type(cls_name, (FeatureTransformer,), {"__init__": __init__,
                                                  "__doc__": doc})


class ChannelNormalize(FeatureTransformer):
    """Reference image.py:377 — note the arg-ORDER delta: the reference
    takes R, G, B means/stds; the native class takes B, G, R (BGR images,
    reference pipeline heritage). Mapped here so reference calls like
    ChannelNormalize(123, 117, 104) normalize the right channels."""

    def __init__(self, mean_r, mean_g, mean_b, std_r=1.0, std_g=1.0,
                 std_b=1.0, bigdl_type="float"):
        super().__init__(_V.ChannelNormalize(
            mean_b=mean_b, mean_g=mean_g, mean_r=mean_r,
            std_b=std_b, std_g=std_g, std_r=std_r), bigdl_type)


class AspectScale(FeatureTransformer):
    """Reference image.py:608. scale_multiple_of/resize_mode variants are
    not in the native surface; non-default values raise loudly."""

    def __init__(self, min_size, scale_multiple_of=1, max_size=1000,
                 resize_mode=1, use_scale_factor=True, min_scale=-1.0,
                 bigdl_type="float"):
        if scale_multiple_of != 1 or resize_mode != 1:
            raise NotImplementedError(
                "AspectScale: scale_multiple_of/resize_mode variants are "
                "not supported; use bigdl_tpu.transform.vision.AspectScale")
        super().__init__(_V.AspectScale(min_size, max_size=max_size),
                         bigdl_type)


class Resize(FeatureTransformer):
    """Reference image.py Resize(resize_h, resize_w, resize_mode,
    use_scale_factor); only the default interpolation is native."""

    def __init__(self, resize_h, resize_w, resize_mode=1,
                 use_scale_factor=True, bigdl_type="float"):
        if resize_mode != 1:
            raise NotImplementedError(
                "Resize: resize_mode != 1 (random interpolation) is not "
                "supported; use bigdl_tpu.transform.vision.Resize")
        super().__init__(_V.Resize(resize_h, resize_w), bigdl_type)


_EXPLICIT = {"FeatureTransformer", "Pipeline", "ImageFeature",
             "ImageFrame", "LocalImageFrame", "DistributedImageFrame",
             "ChannelNormalize", "AspectScale", "Resize"}
__all__ = sorted(_EXPLICIT)
_module = sys.modules[__name__]
for _name in ("HFlip", "Brightness", "ChannelOrder", "Contrast",
              "Saturation", "Hue", "RandomCrop",
              "CenterCrop", "FixedCrop", "Expand", "Filler",
              "RandomTransformer", "ColorJitter", "RoiHFlip", "RoiResize",
              "RoiNormalize", "MatToFloats", "MatToTensor",
              "ImageFrameToSample", "ChannelScaledNormalizer",
              "RandomAlterAspect", "RandomCropper", "RandomResize",
              "Lighting"):
    if hasattr(_V, _name):
        setattr(_module, _name, _passthrough(_name))
        __all__.append(_name)
