"""pyspark-BigDL API compatibility: `bigdl.optim.optimizer`.

Parity: reference pyspark/bigdl/optim/optimizer.py:814 (`Optimizer`), :927
(`DistriOptimizer`), :967 (`LocalOptimizer`) plus OptimMethods, learning
rate schedules, triggers, validation methods, summaries and regularizers.

The reference distinguishes a py4j-driven DistriOptimizer (RDD input) from
a LocalOptimizer (ndarray input); here both feed the same TPU-native
training loop (`bigdl_tpu.optim`) — `training_rdd` accepts a plain list of
`Sample`s (the declared RDD -> list swap) and `(X, y)` ndarray pairs keep
the LocalOptimizer signature.

Arg-name note: the pyspark surface spells hyperparameters without
underscores (`learningrate`, `weightdecay`, `decayrate`) — kept verbatim
here, mapped onto the native snake_case constructors.
"""

from __future__ import annotations

import multiprocessing
import os
from typing import Any, List, Optional

import numpy as np

import bigdl_tpu.optim as _optim
from bigdl_tpu.optim import trigger as _trigger
from bigdl.util.common import (EvaluatedResult, JavaValue, JTensor, Sample,
                               to_list)

DOUBLEMAX = 1.7976931348623157e308


# ---------------------------------------------------------------------------
# validation methods
# ---------------------------------------------------------------------------

class _ValMethod(JavaValue):
    def __init__(self, tpu_method, bigdl_type="float"):
        self.value = tpu_method
        self.bigdl_type = bigdl_type

    def __str__(self):
        return type(self).__name__


class Top1Accuracy(_ValMethod):
    """Reference optimizer.py:41 (1-based labels, as there)."""

    def __init__(self, cri=None, bigdl_type="float"):
        super().__init__(_optim.Top1Accuracy(), bigdl_type)


class Top5Accuracy(_ValMethod):
    def __init__(self, cri=None, bigdl_type="float"):
        super().__init__(_optim.Top5Accuracy(), bigdl_type)


class TreeNNAccuracy(_ValMethod):
    def __init__(self, bigdl_type="float"):
        super().__init__(_optim.TreeNNAccuracy(), bigdl_type)


class Loss(_ValMethod):
    def __init__(self, cri=None, bigdl_type="float"):
        tpu_cri = getattr(cri, "value", cri)
        super().__init__(_optim.Loss(tpu_cri), bigdl_type)


class HitRatio(_ValMethod):
    def __init__(self, k=10, neg_num=100, bigdl_type="float"):
        super().__init__(_optim.HitRatio(k, neg_num), bigdl_type)


class NDCG(_ValMethod):
    def __init__(self, k=10, neg_num=100, bigdl_type="float"):
        super().__init__(_optim.NDCG(k, neg_num), bigdl_type)


class MAE(_ValMethod):
    def __init__(self, bigdl_type="float"):
        super().__init__(_optim.MAE(), bigdl_type)


# ---------------------------------------------------------------------------
# triggers
# ---------------------------------------------------------------------------

class _TriggerValue(JavaValue):
    def __init__(self, tpu_trigger, bigdl_type="float"):
        self.value = tpu_trigger
        self.bigdl_type = bigdl_type


class MaxIteration(_TriggerValue):
    """Reference optimizer.py:135."""

    def __init__(self, max, bigdl_type="float"):
        super().__init__(_trigger.max_iteration(max), bigdl_type)


class MaxEpoch(_TriggerValue):
    """Reference optimizer.py:157."""

    def __init__(self, max_epoch, bigdl_type="float"):
        super().__init__(_trigger.max_epoch(max_epoch), bigdl_type)


class EveryEpoch(_TriggerValue):
    """Reference optimizer.py:179."""

    def __init__(self, bigdl_type="float"):
        super().__init__(_trigger.every_epoch(), bigdl_type)


class SeveralIteration(_TriggerValue):
    """Reference optimizer.py:198."""

    def __init__(self, interval, bigdl_type="float"):
        super().__init__(_trigger.several_iteration(interval), bigdl_type)


class MaxScore(_TriggerValue):
    def __init__(self, max, bigdl_type="float"):
        super().__init__(_trigger.max_score(max), bigdl_type)


class MinLoss(_TriggerValue):
    def __init__(self, min, bigdl_type="float"):
        super().__init__(_trigger.min_loss(min), bigdl_type)


class TriggerAnd(_TriggerValue):
    def __init__(self, first, *other):
        ts = [getattr(t, "value", t) for t in (first,) + other]
        super().__init__(_trigger.and_(*ts), "float")


class TriggerOr(_TriggerValue):
    def __init__(self, first, *other):
        ts = [getattr(t, "value", t) for t in (first,) + other]
        super().__init__(_trigger.or_(*ts), "float")


# ---------------------------------------------------------------------------
# learning-rate schedules
# ---------------------------------------------------------------------------

class _Schedule(JavaValue):
    def __init__(self, tpu_schedule, bigdl_type="float"):
        self.value = tpu_schedule
        self.bigdl_type = bigdl_type


class Poly(_Schedule):
    def __init__(self, power, max_iteration, bigdl_type="float"):
        super().__init__(_optim.Poly(power, max_iteration), bigdl_type)


class Exponential(_Schedule):
    def __init__(self, decay_step, decay_rate, stair_case=False,
                 bigdl_type="float"):
        super().__init__(_optim.Exponential(decay_step, decay_rate,
                                            staircase=stair_case), bigdl_type)


class Step(_Schedule):
    def __init__(self, step_size, gamma, bigdl_type="float"):
        super().__init__(_optim.Step(step_size, gamma), bigdl_type)


class Default(_Schedule):
    def __init__(self, bigdl_type="float"):
        super().__init__(_optim.Default(), bigdl_type)


class Plateau(_Schedule):
    def __init__(self, monitor, factor=0.1, patience=10, mode="min",
                 epsilon=1e-4, cooldown=0, min_lr=0.0, bigdl_type="float"):
        super().__init__(_optim.Plateau(monitor, factor, patience, mode,
                                        epsilon, cooldown, min_lr),
                         bigdl_type)


class Warmup(_Schedule):
    def __init__(self, delta, bigdl_type="float"):
        super().__init__(_optim.Warmup(delta), bigdl_type)


class MultiStep(_Schedule):
    def __init__(self, step_sizes, gamma, bigdl_type="float"):
        super().__init__(_optim.MultiStep(step_sizes, gamma), bigdl_type)


class SequentialSchedule(_Schedule):
    def __init__(self, iteration_per_epoch, bigdl_type="float"):
        super().__init__(_optim.SequentialSchedule(iteration_per_epoch),
                         bigdl_type)

    def add(self, scheduler, max_iteration, bigdl_type="float"):
        self.value.add(getattr(scheduler, "value", scheduler), max_iteration)
        return self


# ---------------------------------------------------------------------------
# optim methods (pyspark arg spellings preserved)
# ---------------------------------------------------------------------------

class OptimMethod(JavaValue):
    """Reference optimizer.py:434."""

    def __init__(self, jvalue, bigdl_type="float", *args):
        self.value = jvalue
        self.bigdl_type = bigdl_type

    @staticmethod
    def load(path, bigdl_type="float"):
        import pickle
        with open(path, "rb") as f:
            return OptimMethod(pickle.load(f), bigdl_type)

    def save(self, path, overWrite=False):
        import pickle
        if not overWrite and os.path.exists(path):
            raise RuntimeError(f"file exists: {path} (overWrite=False)")
        with open(path, "wb") as f:
            pickle.dump(self.value, f)
        return self


class SGD(OptimMethod):
    """Reference optimizer.py:462 (arg spellings verbatim, including the
    reference's own `leaningrate_schedule` typo)."""

    def __init__(self, learningrate=1e-3, learningrate_decay=0.0,
                 weightdecay=0.0, momentum=0.0, dampening=DOUBLEMAX,
                 nesterov=False, leaningrate_schedule=None,
                 learningrates=None, weightdecays=None, bigdl_type="float"):
        if learningrates is not None or weightdecays is not None:
            raise NotImplementedError(
                "per-parameter learningrates/weightdecays: use "
                "set_optim_methods with per-submodule methods")
        sched = getattr(leaningrate_schedule, "value", leaningrate_schedule)
        super().__init__(_optim.SGD(
            learning_rate=learningrate,
            learning_rate_decay=learningrate_decay,
            weight_decay=weightdecay, momentum=momentum,
            dampening=None if dampening == DOUBLEMAX else dampening,
            nesterov=nesterov, learning_rate_schedule=sched), bigdl_type)


class Adagrad(OptimMethod):
    def __init__(self, learningrate=1e-3, learningrate_decay=0.0,
                 weightdecay=0.0, bigdl_type="float"):
        super().__init__(_optim.Adagrad(
            learning_rate=learningrate,
            learning_rate_decay=learningrate_decay,
            weight_decay=weightdecay), bigdl_type)


class LBFGS(OptimMethod):
    def __init__(self, max_iter=20, max_eval=DOUBLEMAX, tolfun=1e-5,
                 tolx=1e-9, ncorrection=100, learningrate=1.0,
                 verbose=False, linesearch=None, linesearch_options=None,
                 bigdl_type="float"):
        if linesearch is not None:
            raise NotImplementedError("custom linesearch functions")
        super().__init__(_optim.LBFGS(
            max_iter=max_iter,
            max_eval=None if max_eval == DOUBLEMAX else max_eval,
            tol_fun=tolfun, tol_x=tolx, n_correction=ncorrection,
            learning_rate=learningrate), bigdl_type)


class Adadelta(OptimMethod):
    def __init__(self, decayrate=0.9, epsilon=1e-10, bigdl_type="float"):
        super().__init__(_optim.Adadelta(decay_rate=decayrate,
                                         epsilon=epsilon), bigdl_type)


class Adam(OptimMethod):
    def __init__(self, learningrate=1e-3, learningrate_decay=0.0,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, bigdl_type="float"):
        super().__init__(_optim.Adam(
            learning_rate=learningrate,
            learning_rate_decay=learningrate_decay,
            beta1=beta1, beta2=beta2, epsilon=epsilon), bigdl_type)


class ParallelAdam(OptimMethod):
    def __init__(self, learningrate=1e-3, learningrate_decay=0.0,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, parallel_num=-1,
                 bigdl_type="float"):
        # parallel_num sized the reference's update-thread pool; the
        # native update is one fused SPMD step over the mesh, so the
        # knob has nothing to configure here
        super().__init__(_optim.ParallelAdam(
            learning_rate=learningrate,
            learning_rate_decay=learningrate_decay,
            beta1=beta1, beta2=beta2, epsilon=epsilon), bigdl_type)


class Ftrl(OptimMethod):
    def __init__(self, learningrate=1e-3, learningrate_power=-0.5,
                 initial_accumulator_value=0.1,
                 l1_regularization_strength=0.0,
                 l2_regularization_strength=0.0,
                 l2_shrinkage_regularization_strength=0.0,
                 bigdl_type="float"):
        super().__init__(_optim.Ftrl(
            learning_rate=learningrate,
            learning_rate_power=learningrate_power,
            initial_accumulator_value=initial_accumulator_value,
            l1_regularization_strength=l1_regularization_strength,
            l2_regularization_strength=l2_regularization_strength,
            l2_shrinkage_regularization_strength=
            l2_shrinkage_regularization_strength), bigdl_type)


class Adamax(OptimMethod):
    def __init__(self, learningrate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-38, bigdl_type="float"):
        super().__init__(_optim.Adamax(
            learning_rate=learningrate, beta1=beta1, beta2=beta2,
            epsilon=epsilon), bigdl_type)


class RMSprop(OptimMethod):
    def __init__(self, learningrate=1e-2, learningrate_decay=0.0,
                 decayrate=0.99, epsilon=1e-8, bigdl_type="float"):
        super().__init__(_optim.RMSprop(
            learning_rate=learningrate,
            learning_rate_decay=learningrate_decay,
            decay_rate=decayrate, epsilon=epsilon), bigdl_type)


# ---------------------------------------------------------------------------
# regularizers
# ---------------------------------------------------------------------------

class L1L2Regularizer(JavaValue):
    def __init__(self, l1, l2, bigdl_type="float"):
        self.value = _optim.L1L2Regularizer(l1, l2)
        self.bigdl_type = bigdl_type


class L1Regularizer(JavaValue):
    def __init__(self, l1, bigdl_type="float"):
        self.value = _optim.L1Regularizer(l1)
        self.bigdl_type = bigdl_type


class L2Regularizer(JavaValue):
    def __init__(self, l2, bigdl_type="float"):
        self.value = _optim.L2Regularizer(l2)
        self.bigdl_type = bigdl_type


class ActivityRegularization(JavaValue):
    def __init__(self, l1, l2, bigdl_type="float"):
        import bigdl_tpu.nn as _nn
        self.value = _nn.ActivityRegularization(l1, l2)
        self.bigdl_type = bigdl_type


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------

class TrainSummary(JavaValue):
    """Reference optimizer.py:1026 — TensorBoard-format training logs."""

    def __init__(self, log_dir, app_name, bigdl_type="float"):
        from bigdl_tpu.visualization import TrainSummary as _TS
        self.value = _TS(log_dir, app_name)
        self.bigdl_type = bigdl_type

    def read_scalar(self, tag):
        return self.value.read_scalar(tag)

    def set_summary_trigger(self, name, trigger):
        self.value.set_summary_trigger(name, getattr(trigger, "value",
                                                     trigger))
        return self


class ValidationSummary(JavaValue):
    """Reference optimizer.py:1074."""

    def __init__(self, log_dir, app_name, bigdl_type="float"):
        from bigdl_tpu.visualization import ValidationSummary as _VS
        self.value = _VS(log_dir, app_name)
        self.bigdl_type = bigdl_type

    def read_scalar(self, tag):
        return self.value.read_scalar(tag)


# ---------------------------------------------------------------------------
# optimizers
# ---------------------------------------------------------------------------

def _to_tpu_samples(rdd):
    """The declared RDD -> list swap: a list (or any iterable) of compat
    Samples / native Samples / (x, y) tuples."""
    from bigdl_tpu.dataset import Sample as TpuSample
    out = []
    for s in rdd:
        if isinstance(s, Sample):
            out.append(s._to_tpu_sample())
        elif isinstance(s, TpuSample):
            out.append(s)
        elif isinstance(s, tuple) and len(s) == 2:
            out.append(TpuSample(np.asarray(s[0]), np.asarray(s[1])))
        else:
            raise TypeError(f"cannot convert {type(s)} to Sample")
    return out


class BaseOptimizer(JavaValue):
    """Reference optimizer.py:698 — fluent configuration over the native
    optimizer stored in `.value`."""

    def set_model(self, model):
        self.value.model = model.value

    def set_checkpoint(self, checkpoint_trigger, checkpoint_path,
                       isOverWrite=True):
        # native signature is (path, trigger); isOverWrite is the native
        # default behavior (checkpoints are versioned by iteration), and
        # save_checkpoint creates the (possibly remote-URI) dir itself
        self.value.set_checkpoint(checkpoint_path,
                                  getattr(checkpoint_trigger, "value",
                                          checkpoint_trigger))

    def set_gradclip_const(self, min_value, max_value):
        self.value.set_constant_gradient_clipping(min_value, max_value)

    def set_gradclip_l2norm(self, clip_norm):
        self.value.set_gradient_clipping_by_l2_norm(clip_norm)

    def disable_gradclip(self):
        self.value.disable_gradient_clipping()

    def optimize(self):
        """Run the optimization; returns the trained model wrapper."""
        from bigdl.nn.layer import Layer
        trained = self.value.optimize()
        return Layer.of(trained)

    def set_train_summary(self, summary):
        self.value.set_train_summary(summary.value)
        return self

    def set_val_summary(self, summary):
        self.value.set_validation_summary(summary.value)
        return self

    def prepare_input(self):
        pass

    def set_end_when(self, end_when):
        self.value.set_end_when(getattr(end_when, "value", end_when))
        return self


class Optimizer(BaseOptimizer):
    """Reference optimizer.py:814 — the RDD-driven front door."""

    def __init__(self, model, training_rdd, criterion, end_trigger,
                 batch_size, optim_method=None, bigdl_type="float"):
        self.pvalue = DistriOptimizer(model, training_rdd, criterion,
                                      end_trigger, batch_size, optim_method,
                                      bigdl_type)
        self.value = self.pvalue.value
        self.bigdl_type = self.pvalue.bigdl_type

    @staticmethod
    def create(model, training_set, criterion, end_trigger=None,
               batch_size=32, optim_method=None, cores=None,
               bigdl_type="float"):
        if not end_trigger:
            end_trigger = MaxEpoch(1)
        if not optim_method:
            optim_method = SGD()
        if isinstance(training_set, tuple) and len(training_set) == 2:
            x, y = training_set
            return LocalOptimizer(X=x, Y=y, model=model, criterion=criterion,
                                  end_trigger=end_trigger,
                                  batch_size=batch_size,
                                  optim_method=optim_method, cores=cores,
                                  bigdl_type=bigdl_type)
        return DistriOptimizer(model=model, training_rdd=training_set,
                               criterion=criterion, end_trigger=end_trigger,
                               batch_size=batch_size,
                               optim_method=optim_method,
                               bigdl_type=bigdl_type)

    def set_validation(self, batch_size, val_rdd, trigger, val_method=None):
        if val_method is None:
            val_method = [Top1Accuracy()]
        self.value.set_validation(
            getattr(trigger, "value", trigger), _to_tpu_samples(val_rdd),
            [m.value for m in to_list(val_method)], batch_size=batch_size)

    def set_traindata(self, training_rdd, batch_size):
        from bigdl_tpu.optim.optimizer import _as_batched_dataset
        self.value.dataset = _as_batched_dataset(
            _to_tpu_samples(training_rdd), batch_size, drop_remainder=False)


class DistriOptimizer(Optimizer):
    """Reference optimizer.py:927. `training_rdd` is the declared
    RDD -> list swap; everything else is signature-identical."""

    def __init__(self, model, training_rdd, criterion, end_trigger,
                 batch_size, optim_method=None, bigdl_type="float"):
        from bigdl_tpu.optim.optimizer import Optimizer as _TpuOptimizer
        samples = _to_tpu_samples(training_rdd)
        opt = _TpuOptimizer(model.value, samples,
                            getattr(criterion, "value", criterion),
                            batch_size=batch_size)
        self.value = opt
        self.bigdl_type = bigdl_type
        if end_trigger is not None:
            opt.set_end_when(getattr(end_trigger, "value", end_trigger))
        if optim_method is not None:
            if isinstance(optim_method, dict):
                opt.set_optim_methods({k: v.value for k, v
                                       in optim_method.items()})
            else:
                opt.set_optim_method(getattr(optim_method, "value",
                                             optim_method))


class LocalOptimizer(BaseOptimizer):
    """Reference optimizer.py:967 — ndarray-fed local training."""

    def __init__(self, X, Y, model, criterion, end_trigger, batch_size,
                 optim_method=None, cores=None, bigdl_type="float"):
        from bigdl_tpu.optim.optimizer import Optimizer as _TpuOptimizer
        xs = [np.asarray(x) for x in to_list(X)]
        y = np.asarray(Y)
        if len(xs) != 1:
            from bigdl_tpu.dataset import Sample as TpuSample
            data = [TpuSample([x[i] for x in xs], y[i])
                    for i in range(len(y))]
        else:
            data = (xs[0], y)
        opt = _TpuOptimizer(model.value, data,
                            getattr(criterion, "value", criterion),
                            batch_size=batch_size, local=True)
        self.value = opt
        self.bigdl_type = bigdl_type
        if end_trigger is not None:
            opt.set_end_when(getattr(end_trigger, "value", end_trigger))
        if optim_method is not None:
            opt.set_optim_method(getattr(optim_method, "value",
                                         optim_method))

    def set_validation(self, batch_size, X_val, Y_val, trigger,
                       val_method=None):
        if val_method is None:
            val_method = [Top1Accuracy()]
        xs = [np.asarray(x) for x in to_list(X_val)]
        y = np.asarray(Y_val)
        from bigdl_tpu.dataset import Sample as TpuSample
        data = [TpuSample([x[i] for x in xs] if len(xs) > 1 else xs[0][i],
                          y[i]) for i in range(len(y))]
        self.value.set_validation(getattr(trigger, "value", trigger), data,
                                  [m.value for m in to_list(val_method)],
                                  batch_size=batch_size)
