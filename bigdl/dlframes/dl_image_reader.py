"""pyspark-BigDL API compatibility: `bigdl.dlframes.dl_image_reader`.

Parity: reference pyspark/bigdl/dlframes/dl_image_reader.py —
`DLImageReader.readImages(path)` loads a directory/glob of images into
a DataFrame with one `image` struct column
(origin/height/width/nChannels/data). Spark-free delta: the frame is
pandas (the dlframes stages consume either), `sc`/partition args are
accepted and ignored.
"""

from __future__ import annotations


class DLImageReader:

    @staticmethod
    def readImages(path, sc=None, minParitions=1, bigdl_type="float"):
        from bigdl_tpu.dlframes.dl_image import DLImageReader as _R
        return _R.read(path)

    # pep8 spelling used by newer reference code
    read_images = readImages
