"""pyspark-BigDL API compatibility: `bigdl.dlframes.dl_image_transformer`.

Parity: reference pyspark/bigdl/dlframes/dl_image_transformer.py —
a Spark-ML-style Transformer applying a vision FeatureTransformer to
the image column (input col defaults to `image`, output to `output`,
always the float schema). Works in sklearn-style pipelines over pandas
frames here.
"""

from __future__ import annotations


class DLImageTransformer:

    def __init__(self, transformer, jvalue=None, bigdl_type="float"):
        from bigdl_tpu.dlframes.dl_image import DLImageTransformer as _T
        native = getattr(transformer, "value", transformer)
        self.value = _T(native)
        self.bigdl_type = bigdl_type

    def setInputCol(self, value):
        self.value.input_col = value
        return self

    def setOutputCol(self, value):
        self.value.output_col = value
        return self

    def transform(self, dataset):
        return self.value.transform(dataset)
