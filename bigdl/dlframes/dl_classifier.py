"""pyspark-BigDL API compatibility: `bigdl.dlframes.dl_classifier`.

Parity: reference pyspark/bigdl/dlframes/dl_classifier.py — the Spark-ML
Estimator/Model/Classifier pipeline stages. Here they delegate to the
TPU-native `bigdl_tpu.dlframes` stages, which implement the same
fit/transform contract over pandas DataFrames (sklearn-compatible; the
declared design delta — no Spark ML runtime exists in this stack, the
DataFrame hand-off is the same RDD -> local swap as everywhere else in
the compat namespace).

The pyspark-style Param setters (setFeaturesCol, setBatchSize, ...) are
kept so reference pipeline-construction code runs unmodified.
"""

from __future__ import annotations

from bigdl.util.common import JavaValue


def _unwrap(v):
    return getattr(v, "value", v)


class _ParamsMixin:
    """The HasFeaturesCol/HasLabelCol/HasPredictionCol/HasBatchSize/
    HasMaxEpoch/HasLearningRate surface (reference dl_classifier.py
    Params classes), as plain fluent setters."""

    def setFeaturesCol(self, v):
        self.value.features_col = v
        return self

    def getFeaturesCol(self):
        return self.value.features_col

    def setLabelCol(self, v):
        self.value.label_col = v
        return self

    def getLabelCol(self):
        return self.value.label_col

    def setPredictionCol(self, v):
        self.value.prediction_col = v
        return self

    def getPredictionCol(self):
        return getattr(self.value, "prediction_col", "prediction")

    def setBatchSize(self, v):
        self.value.set_batch_size(v)
        return self

    def setMaxEpoch(self, v):
        self.value.set_max_epoch(v)
        return self

    def setLearningRate(self, v):
        self.value.set_learning_rate(v)
        return self


class DLEstimator(_ParamsMixin, JavaValue):
    """Reference dl_classifier.py:97."""

    def __init__(self, model, criterion, feature_size, label_size,
                 jvalue=None, bigdl_type="float"):
        from bigdl_tpu.dlframes import DLEstimator as _E
        self.value = jvalue or _E(_unwrap(model), _unwrap(criterion),
                                  feature_size, label_size)
        self.bigdl_type = bigdl_type
        self.featureSize = feature_size

    def fit(self, dataset):
        """dataset: pandas DataFrame (the DataFrame swap). Returns a
        DLModel wrapping the trained network."""
        return DLModel.of(self.value.fit(dataset), self.featureSize,
                          self.bigdl_type)

    _fit = fit


class DLModel(_ParamsMixin, JavaValue):
    """Reference dl_classifier.py:113."""

    def __init__(self, model, featureSize, jvalue=None,
                 bigdl_type="float"):
        if jvalue is None:
            from bigdl_tpu.dlframes import DLModel as _M
            jvalue = _M(_unwrap(model), featureSize)
        self.value = jvalue
        self.bigdl_type = bigdl_type
        self.featureSize = featureSize

    def setFeatureSize(self, v):
        self.value.feature_size = v
        self.featureSize = v
        return self

    def getFeatureSize(self):
        return self.featureSize

    def transform(self, dataset):
        return self.value.transform(dataset)

    _transform = transform

    @classmethod
    def of(cls, jvalue, feature_size=None, bigdl_type="float"):
        return cls(model=None, featureSize=feature_size, jvalue=jvalue,
                   bigdl_type=bigdl_type)


class DLClassifier(DLEstimator):
    """Reference dl_classifier.py:130 — label_size fixed to [1]."""

    def __init__(self, model, criterion, feature_size, bigdl_type="float"):
        from bigdl_tpu.dlframes import DLClassifier as _C
        JavaValue.__init__(self, _C(_unwrap(model), _unwrap(criterion),
                                    feature_size), bigdl_type)
        self.featureSize = feature_size

    def fit(self, dataset):
        return DLClassifierModel.of(self.value.fit(dataset),
                                    self.featureSize, self.bigdl_type)

    _fit = fit


class DLClassifierModel(DLModel):
    """Reference dl_classifier.py:140."""
