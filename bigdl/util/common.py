"""pyspark-BigDL API compatibility: `bigdl.util.common`.

Parity: reference pyspark/bigdl/util/common.py:100 — the JavaValue /
callBigDlFunc machinery there bridges Python to a JVM over py4j; in this
TPU-native framework the "backend" is the in-process `bigdl_tpu` package,
so `callBigDlFunc` dispatches to plain Python constructors and the
Spark-context helpers become no-ops that keep reference scripts importable
and runnable unmodified (minus the SparkContext itself — the one declared
swap is RDD -> list/ndarray).
"""

from __future__ import annotations

import logging
import os  # noqa: F401  (star-exported: reference scripts rely on
import sys  # noqa: F401  `from bigdl.util.common import *` providing these)
from typing import Any, List, Optional

import numpy as np

_log = logging.getLogger("bigdl.util")


def get_dtype(bigdl_type: str = "float"):
    """Reference pyspark/bigdl/util/common.py get_dtype: always float32."""
    return "float32"


def to_list(a):
    """Reference pyspark/bigdl/util/common.py to_list."""
    if isinstance(a, list):
        return a
    return [a]


class SingletonMixin(object):
    """Reference pyspark/bigdl/util/common.py SingletonMixin."""

    _instance = None

    @classmethod
    def instance(cls, *args, **kwargs):
        if cls._instance is None:
            cls._instance = cls(*args, **kwargs)
        return cls._instance


class JavaValue(object):
    """Reference pyspark/bigdl/util/common.py:100 JavaValue.

    In the reference, `__init__` calls `callBigDlFunc(bigdl_type,
    "create<ClassName>", *args)` through py4j and stores the resulting JVM
    handle in `self.value`. Here `self.value` holds the in-process
    `bigdl_tpu` object the subclass constructed — same field name, so code
    that passes `.value` around keeps working.
    """

    def jvm_class_constructor(self):
        return "create" + self.__class__.__name__

    def __init__(self, jvalue, bigdl_type="float", *args):
        self.value = jvalue if jvalue is not None else callBigDlFunc(
            bigdl_type, self.jvm_class_constructor(), *args)
        self.bigdl_type = bigdl_type

    def __str__(self):
        return str(self.value)


def callBigDlFunc(bigdl_type: str, name: str, *args):
    """In-process stand-in for the reference's py4j dispatch
    (pyspark/bigdl/util/common.py callBigDlFunc).

    Supports the `create<ClassName>` pattern by resolving the class in
    `bigdl_tpu`'s nn / optim namespaces. Anything else raises with a
    pointer to the native `bigdl_tpu` API, which covers the full surface.
    """
    if name.startswith("create"):
        cls_name = name[len("create"):]
        import bigdl_tpu.nn as _nn
        import bigdl_tpu.optim as _optim
        for ns in (_nn, _optim):
            cls = getattr(ns, cls_name, None)
            if cls is not None:
                return cls(*args)
    raise NotImplementedError(
        f"callBigDlFunc({name!r}): no JVM here — use the equivalent "
        f"bigdl_tpu API (see docs/MIGRATION.md)")


def callJavaFunc(func, *args):
    """Reference pyspark/bigdl/util/common.py callJavaFunc: direct call."""
    return func(*args)


class JTensor(object):
    """Reference pyspark/bigdl/util/common.py JTensor: the ndarray wrapper
    used to ship tensors across the py4j bridge. Kept bit-compatible
    (storage + int32 shape (+ indices for sparse)) so user code that builds
    or unpacks JTensors runs unmodified; `to_ndarray` is now free.
    """

    def __init__(self, storage, shape, bigdl_type="float", indices=None):
        if isinstance(storage, bytes) and isinstance(shape, bytes):
            self.storage = np.frombuffer(storage, dtype=get_dtype(bigdl_type))
            self.shape = np.frombuffer(shape, dtype=np.int32)
        else:
            self.storage = np.array(storage, dtype=get_dtype(bigdl_type))
            self.shape = np.array(shape, dtype=np.int32)
        if indices is None:
            self.indices = None
        elif isinstance(indices, bytes):
            self.indices = np.frombuffer(indices, dtype=np.int32)
        else:
            assert isinstance(indices, np.ndarray), \
                f"indices should be a np.ndarray, not {type(indices)}"
            self.indices = np.array(indices, dtype=np.int32)
        self.bigdl_type = bigdl_type

    @classmethod
    def from_ndarray(cls, a_ndarray, bigdl_type="float"):
        if a_ndarray is None:
            return None
        assert isinstance(a_ndarray, np.ndarray), \
            f"input should be a np.ndarray, not {type(a_ndarray)}"
        return cls(a_ndarray, a_ndarray.shape, bigdl_type)

    @classmethod
    def sparse(cls, a_ndarray, i_ndarray, shape, bigdl_type="float"):
        """Sparse JTensor from values + indices (reference layout: the
        indices array is the concatenation of one row per dimension)."""
        assert isinstance(a_ndarray, np.ndarray)
        assert isinstance(i_ndarray, np.ndarray)
        assert i_ndarray.size == a_ndarray.size * shape.size, \
            (f"size of values {a_ndarray.size} * shape {shape.size} != "
             f"indices {i_ndarray.size}")
        return cls(a_ndarray, shape, bigdl_type, i_ndarray)

    def to_ndarray(self):
        return np.asarray(self.storage, dtype=get_dtype(self.bigdl_type)
                          ).reshape(tuple(int(s) for s in self.shape))

    def __reduce__(self):
        if self.indices is None:
            return JTensor, (self.storage.tobytes(), self.shape.tobytes(),
                             self.bigdl_type)
        return JTensor, (self.storage.tobytes(), self.shape.tobytes(),
                         self.bigdl_type, self.indices.tobytes())

    def __str__(self):
        return (f"JTensor: storage: {self.storage}, shape: {self.shape}"
                + (f", indices: {self.indices}" if self.indices is not None
                   else ""))

    def __repr__(self):
        return self.__str__()


class Sample(object):
    """Reference pyspark/bigdl/util/common.py:291 Sample — features +
    labels, each a list of JTensors."""

    def __init__(self, features, labels, bigdl_type="float"):
        self.feature = features[0]
        self.features = features
        self.label = labels[0]
        self.labels = labels
        self.bigdl_type = bigdl_type

    @classmethod
    def from_ndarray(cls, features, labels, bigdl_type="float"):
        if isinstance(features, np.ndarray):
            features = [features]
        else:
            assert all(isinstance(f, np.ndarray) for f in features), \
                f"features should be a list of np.ndarray, not {type(features)}"
        if np.isscalar(labels):
            labels = [np.array(labels)]
        elif isinstance(labels, np.ndarray):
            labels = [labels]
        else:
            assert all(isinstance(l, np.ndarray) for l in labels), \
                f"labels should be a list of np.ndarray, not {type(labels)}"
        return cls(
            features=[JTensor.from_ndarray(f) for f in features],
            labels=[JTensor.from_ndarray(l) for l in labels],
            bigdl_type=bigdl_type)

    @classmethod
    def from_jtensor(cls, features, labels, bigdl_type="float"):
        if isinstance(features, JTensor):
            features = [features]
        else:
            assert all(isinstance(f, JTensor) for f in features), \
                f"features should be a list of JTensor, not {type(features)}"
        if np.isscalar(labels):
            labels = [JTensor.from_ndarray(np.array(labels))]
        elif isinstance(labels, JTensor):
            labels = [labels]
        else:
            assert all(isinstance(l, JTensor) for l in labels), \
                f"labels should be a list of JTensor, not {type(labels)}"
        return cls(features=features, labels=labels, bigdl_type=bigdl_type)

    def _to_tpu_sample(self):
        """Convert to the native `bigdl_tpu.dataset.Sample`."""
        from bigdl_tpu.dataset import Sample as TpuSample
        return TpuSample([f.to_ndarray() for f in self.features],
                         [l.to_ndarray() for l in self.labels])

    def __reduce__(self):
        return Sample, (self.features, self.labels, self.bigdl_type)

    def __str__(self):
        return f"Sample: features: {self.features}, labels: {self.labels}"

    def __repr__(self):
        return self.__str__()


class EvaluatedResult(object):
    """Reference pyspark/bigdl/util/common.py EvaluatedResult."""

    def __init__(self, result, total_num, method):
        self.result = result
        self.total_num = total_num
        self.method = method

    def __reduce__(self):
        return EvaluatedResult, (self.result, self.total_num, self.method)

    def __str__(self):
        return (f"Evaluated result: {self.result}, total_num: "
                f"{self.total_num}, method: {self.method}")


class JActivity(object):
    def __init__(self, value):
        self.value = value


class RNG:
    """Reference pyspark/bigdl/util/common.py RNG — delegates to the
    framework generator (bigdl_tpu RandomGenerator, MT-parity with the
    reference's com.intel.analytics.bigdl.utils.RandomGenerator)."""

    def __init__(self, bigdl_type="float"):
        self.bigdl_type = bigdl_type

    def set_seed(self, seed):
        from bigdl_tpu.utils.random_generator import RNG as _rng
        _rng.setSeed(seed)

    def uniform(self, a, b, size):
        from bigdl_tpu.utils.random_generator import RNG as _rng
        return np.asarray(_rng.uniform(a, b, size=size))


def init_engine(bigdl_type="float"):
    """Reference pyspark/bigdl/util/common.py init_engine: initializes the
    executor-side engine. Here: `bigdl_tpu.utils.engine.Engine.init`."""
    from bigdl_tpu.utils.engine import Engine
    Engine.init()


def get_node_and_core_number(bigdl_type="float"):
    """Reference: (node_number, core_number) from the Engine."""
    from bigdl_tpu.utils.engine import Engine
    import jax
    return Engine.node_number(), jax.local_device_count()


def init_executor_gateway(sc, bigdl_type="float"):
    """No py4j gateway to start — kept importable for reference scripts."""
    _log.info("init_executor_gateway: no-op (in-process backend)")


def redire_spark_logs(bigdl_type="float", log_path=None):
    """Reference redirects Spark logs into a file; here a no-op that keeps
    reference driver scripts runnable."""
    _log.debug("redire_spark_logs: no-op (no Spark JVM)")


def show_bigdl_info_logs(bigdl_type="float"):
    logging.getLogger("bigdl_tpu").setLevel(logging.INFO)
    logging.getLogger("bigdl_tpu.optim").setLevel(logging.INFO)


def get_spark_context(conf=None):
    """Reference returns the active SparkContext. Without Spark there is no
    context object; raise with the migration pointer instead of a silent
    fake — reference scripts' `sc` usages are exactly the RDD swap sites."""
    raise RuntimeError(
        "No Spark runtime in bigdl-tpu: pass plain lists/ndarrays instead "
        "of RDDs (see docs/MIGRATION.md, 'pyspark compatibility')")


class SparkConf(dict):
    """Minimal stand-in for pyspark.SparkConf so `create_spark_conf()`
    keeps working in reference scripts; settings are recorded but unused."""

    def set(self, key, value):
        self[key] = value
        return self

    def setAppName(self, name):
        return self.set("spark.app.name", name)

    def setMaster(self, master):
        return self.set("spark.master", master)

    def get(self, key, default=None):  # dict.get already matches
        return super().get(key, default)


def create_spark_conf():
    """Reference builds a SparkConf preloaded with BigDL properties
    (pyspark/bigdl/util/common.py create_spark_conf). Returns the stub
    conf; `Engine.config` is the real configuration surface."""
    return SparkConf()


def get_activities(activities):
    return activities


def _py2java(gateway, obj):  # pragma: no cover - compat shim
    return obj


def _java2py(gateway, r, encoding="bytes"):  # pragma: no cover - compat shim
    return r
