"""pyspark-BigDL API compatibility: `bigdl.util.engine`.

Parity: reference pyspark/bigdl/util/engine.py — classpath/SPARK_HOME
bootstrap for the py4j bridge. There is no JVM here, so these are
importable no-ops that keep reference launcher scripts working.
"""

from __future__ import annotations

import logging

_log = logging.getLogger("bigdl.util.engine")


def exist_pyspark():
    try:
        import pyspark  # noqa: F401
        return True
    except ImportError:
        return False


def check_spark_source_conflict(spark_home, pyspark_path):
    pass


def compare_version(version1, version2):
    """Reference engine.py compare_version: 1 / -1 / 0."""
    v1 = [int(x) for x in version1.split(".") if x.isdigit()]
    v2 = [int(x) for x in version2.split(".") if x.isdigit()]
    return (v1 > v2) - (v1 < v2)


def prepare_env():
    _log.debug("prepare_env: no JVM/Spark classpath to prepare")


def get_bigdl_classpath():
    """No jar to locate; returns '' as the reference does pre-build."""
    return ""


def is_spark_below_2_2():
    return False
