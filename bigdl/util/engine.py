"""pyspark-BigDL API compatibility: `bigdl.util.engine`.

Parity: reference pyspark/bigdl/util/engine.py — classpath/SPARK_HOME
bootstrap for the py4j bridge. There is no JVM here, so these are
importable no-ops that keep reference launcher scripts working.
"""

from __future__ import annotations

import logging

_log = logging.getLogger("bigdl.util.engine")


def exist_pyspark():
    try:
        import pyspark  # noqa: F401
        return True
    except ImportError:
        return False


def check_spark_source_conflict(spark_home, pyspark_path):
    pass


def compare_version(version1, version2):
    """Reference engine.py:128 compare_version: 1 / -1 / 0, zero-padding
    to equal length so '2.4' == '2.4.0', with non-numeric leading chars
    of a segment handled like the reference's int() of the digit prefix
    ('1-SNAPSHOT' -> 1)."""

    def parts(v):
        out = []
        for seg in v.split("."):
            digits = ""
            for ch in seg:
                if ch.isdigit():
                    digits += ch
                else:
                    break
            out.append(int(digits) if digits else 0)
        return out

    v1, v2 = parts(version1), parts(version2)
    n = max(len(v1), len(v2))
    v1 += [0] * (n - len(v1))
    v2 += [0] * (n - len(v2))
    return (v1 > v2) - (v1 < v2)


def prepare_env():
    _log.debug("prepare_env: no JVM/Spark classpath to prepare")


def get_bigdl_classpath():
    """No jar to locate; returns '' as the reference does pre-build."""
    return ""


def is_spark_below_2_2():
    return False
