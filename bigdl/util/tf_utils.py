"""pyspark-BigDL API compatibility: `bigdl.util.tf_utils`.

Parity: reference pyspark/bigdl/util/tf_utils.py — TensorFlow graph
import/export helpers. The heavy lifting lives in
`bigdl_tpu.interop.tensorflow` (GraphDef loader/saver, 161-op surface);
these wrappers keep the reference entry points importable and delegate.
"""

from __future__ import annotations


def convert(input_ops, output_ops, byte_order="little_endian",
            bigdl_type="float"):
    """Reference tf_utils.convert: TF session graph -> BigDL model.
    Requires a live TF session in the reference; here use
    `Model.load_tensorflow(pb_path, inputs, outputs)` on a frozen
    GraphDef instead."""
    raise NotImplementedError(
        "convert(live TF session): export the graph to a .pb and use "
        "bigdl.nn.layer.Model.load_tensorflow(path, inputs, outputs) "
        "(bigdl_tpu.interop.tensorflow.TensorflowLoader)")


def get_path(output_name, sess=None):
    raise NotImplementedError(
        "get_path needs a live TF session; freeze the graph to .pb and "
        "load it with Model.load_tensorflow")


def export_checkpoint(checkpoint_path):
    raise NotImplementedError(
        "export_checkpoint reads TF V1 checkpoints; use "
        "bigdl_tpu.interop.tensorflow.TensorflowLoader with a frozen "
        "GraphDef (bin_file) instead")


def merge_checkpoint(input_graph, checkpoint, output_node_names,
                     output_graph, sess=None):
    raise NotImplementedError(
        "merge_checkpoint (freeze_graph) requires TensorFlow; freeze "
        "offline and load the .pb via Model.load_tensorflow")
