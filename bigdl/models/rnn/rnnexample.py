"""pyspark-BigDL API compatibility: `bigdl.models.rnn.rnnexample`.

Parity: reference pyspark/bigdl/models/rnn/rnnexample.py — the simple
RNN language model (Recurrent(RnnCell) -> TimeDistributed(Linear)) plus
the Tiny-Shakespeare text preparation helpers, here list-based instead
of RDD-based (declared delta: no Spark in this build) and zero-egress
(download resolves staged files only).
"""

from __future__ import annotations

import os

import numpy as np

from bigdl.dataset import base, sentence
from bigdl.nn.layer import (Linear, Recurrent, RnnCell, Sequential, Tanh,
                            TimeDistributed)

SOURCE_URL = ("https://raw.githubusercontent.com/udibr/"
              "head_lines/master/data/")


def download_data(dest_dir):
    return base.maybe_download("input.txt", dest_dir, SOURCE_URL + "input.txt")


def prepare_data(sc, folder, vocabsize, training_split=0.8):
    """(train_tokens, val_tokens, vocab_size, word->idx dict): sentences
    split, bipadded, tokenized, and capped to the `vocabsize` most
    frequent words (rarer words map to an UNK bucket). `sc` is accepted
    for signature parity and ignored (no Spark)."""
    path = download_data(folder)
    sents = []
    for line in sentence.read_localfile(path):
        for s in sentence.sentences_split(line):
            sents.append(sentence.sentences_bipadding(s))
    tokens = [sentence.sentence_tokenizer(s) for s in sents]
    freq = {}
    for toks in tokens:
        for w in toks:
            freq[w] = freq.get(w, 0) + 1
    vocab = sorted(freq, key=lambda w: -freq[w])[:vocabsize - 1]
    w2i = {w: i + 1 for i, w in enumerate(vocab)}  # 1-based; UNK = last id
    unk = len(w2i) + 1
    idxed = [[w2i.get(w, unk) for w in toks] for toks in tokens]
    split = int(len(idxed) * training_split)
    return idxed[:split], idxed[split:], unk, w2i


def build_model(input_size, hidden_size, output_size):
    model = Sequential()
    model.add(Recurrent()
              .add(RnnCell(input_size, hidden_size, Tanh()))) \
        .add(TimeDistributed(Linear(hidden_size, output_size)))
    model.reset()
    return model
