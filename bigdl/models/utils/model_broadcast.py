"""pyspark-BigDL API compatibility: `bigdl.models.utils.model_broadcast`.

Parity: reference pyspark/bigdl/models/utils/model_broadcast.py — a
Spark Broadcast subclass that ships a model to executors via BigDL's
own serializer instead of pickle. In this single-process runtime there
are no executors; `broadcast_model` round-trips the model through the
protobuf serializer (same wire format role) and `.value` hands back the
reconstructed layer — so ported scripts keep working and the
serialization cost/behavior they relied on is preserved.
"""

from __future__ import annotations

import os
import tempfile


def broadcast_model(sc, layer):
    """`sc` accepted for signature parity and ignored (no Spark)."""
    return ModelBroadcast(layer)


class ModelBroadcast:
    def __init__(self, layer):
        # serialize/deserialize through the real model format (the
        # reference broadcasts the serialized bytes, not the object)
        import shutil
        from bigdl.nn.layer import Layer
        d = tempfile.mkdtemp(prefix="bigdl_broadcast_")
        try:
            path = os.path.join(d, "model.bigdl")
            layer.saveModel(path, over_write=True)
            self._value = Layer.load(path)
        finally:
            shutil.rmtree(d, ignore_errors=True)

    @property
    def value(self):
        return self._value

    def unpersist(self, blocking=False):
        return self

    def destroy(self, blocking=False):
        self._value = None
