"""pyspark-BigDL API compatibility: the LeNet-5 example.

Parity: reference pyspark/bigdl/models/lenet/lenet5.py — the canonical
"does the pyspark API still work" script. `build_model` is the same
channel-first LeNet the reference builds; the `__main__` driver trains it
through the compat `Optimizer` on local MNIST IDX files (lists instead of
RDDs — the one declared swap; there is no spark-submit here).

Run:  python -m bigdl.models.lenet.lenet5 -d /path/to/mnist -n 2
"""

from optparse import OptionParser
import sys

from bigdl.models.lenet.utils import (get_end_trigger, preprocess_mnist,
                                      validate_optimizer)
from bigdl.nn.layer import (Linear, LogSoftMax, Model, Reshape, Sequential,
                            SpatialConvolution, SpatialMaxPooling, Tanh)
from bigdl.nn.criterion import ClassNLLCriterion
from bigdl.optim.optimizer import Optimizer, SGD, Top1Accuracy
from bigdl.util.common import (Sample, create_spark_conf, init_engine,
                               redire_spark_logs, show_bigdl_info_logs)
from bigdl.dataset import mnist
from bigdl.dataset.transformer import normalizer


def build_model(class_num):
    """The reference LeNet-5 topology (pyspark/bigdl/models/lenet/
    lenet5.py build_model), channel-first as there."""
    model = Sequential()
    model.add(Reshape([1, 28, 28]))
    model.add(SpatialConvolution(1, 6, 5, 5))
    model.add(Tanh())
    model.add(SpatialMaxPooling(2, 2, 2, 2))
    model.add(SpatialConvolution(6, 12, 5, 5))
    model.add(Tanh())
    model.add(SpatialMaxPooling(2, 2, 2, 2))
    model.add(Reshape([12 * 4 * 4]))
    model.add(Linear(12 * 4 * 4, 100))
    model.add(Tanh())
    model.add(Linear(100, class_num))
    model.add(LogSoftMax())
    return model


if __name__ == "__main__":
    parser = OptionParser()
    parser.add_option("-a", "--action", dest="action", default="train")
    parser.add_option("-b", "--batchSize", type=int, dest="batchSize",
                      default=128)
    parser.add_option("-o", "--modelPath", dest="modelPath",
                      default="/tmp/lenet5/model.470")
    parser.add_option("-c", "--checkpointPath", dest="checkpointPath",
                      default="/tmp/lenet5")
    parser.add_option("-t", "--endTriggerType", dest="endTriggerType",
                      default="epoch")
    parser.add_option("-n", "--endTriggerNum", type=int,
                      dest="endTriggerNum", default=20)
    parser.add_option("-d", "--dataPath", dest="dataPath",
                      default="/tmp/mnist")

    (options, args) = parser.parse_args(sys.argv)

    create_spark_conf()          # kept for script parity; no Spark here
    redire_spark_logs()
    show_bigdl_info_logs()
    init_engine()

    if options.action == "train":
        (train_data, test_data) = preprocess_mnist(None, options)

        optimizer = Optimizer(
            model=build_model(10),
            training_rdd=train_data,
            criterion=ClassNLLCriterion(),
            optim_method=SGD(learningrate=0.01, learningrate_decay=0.0002),
            end_trigger=get_end_trigger(options),
            batch_size=options.batchSize)
        validate_optimizer(optimizer, test_data, options)
        trained_model = optimizer.optimize()
        parameters = trained_model.parameters()
    elif options.action == "test":
        (images, labels) = mnist.read_data_sets(options.dataPath, "test")
        test_data = [Sample.from_ndarray(
            normalizer(img, mnist.TEST_MEAN, mnist.TEST_STD),
            label + 1) for img, label in zip(images, labels)]
        model = Model.load(options.modelPath)
        results = model.evaluate(test_data, options.batchSize,
                                 [Top1Accuracy()])
        for result in results:
            print(result)
