"""pyspark-BigDL API compatibility: LeNet example helpers.

Parity: reference pyspark/bigdl/models/lenet/utils.py. `sc` parameters
are kept in the signatures for script parity but ignored — data flows as
plain lists instead of RDDs (the declared swap).
"""

from bigdl.dataset import mnist
from bigdl.dataset.transformer import normalizer
from bigdl.optim.optimizer import EveryEpoch, MaxEpoch, MaxIteration, \
    Top1Accuracy
from bigdl.util.common import Sample


def get_mnist(sc, data_type="train", location="/tmp/mnist"):
    """(features ndarray, 1-based label) records — reference get_mnist
    without the RDD parallelize."""
    (images, labels) = mnist.read_data_sets(location, data_type)
    return list(zip(images, labels + 1))  # Target start from 1 in BigDL


def preprocess_mnist(sc, options):
    """Normalize and wrap into Samples (reference preprocess_mnist)."""
    train_data = [
        Sample.from_ndarray(normalizer(img, mnist.TRAIN_MEAN,
                                       mnist.TRAIN_STD), label)
        for img, label in get_mnist(sc, "train", options.dataPath)]
    test_data = [
        Sample.from_ndarray(normalizer(img, mnist.TEST_MEAN,
                                       mnist.TEST_STD), label)
        for img, label in get_mnist(sc, "test", options.dataPath)]
    return train_data, test_data


def get_end_trigger(options):
    """Reference get_end_trigger."""
    if options.endTriggerType.lower() == "epoch":
        return MaxEpoch(options.endTriggerNum)
    return MaxIteration(options.endTriggerNum)


def validate_optimizer(optimizer, test_data, options):
    """Reference validate_optimizer."""
    optimizer.set_validation(
        batch_size=options.batchSize,
        val_rdd=test_data,
        trigger=EveryEpoch(),
        val_method=[Top1Accuracy()]
    )
    optimizer.set_checkpoint(EveryEpoch(), options.checkpointPath)
