"""pyspark-BigDL API compatibility: `bigdl.models.ml_pipeline`.

Parity: reference pyspark/bigdl/models/ml_pipeline/dl_classifier.py —
the Spark-ML pipeline stages. These are the same classes the reference
later moved to bigdl.dlframes; this module re-exports our dlframes
implementations under the old import path so either spelling works.
"""

from bigdl.dlframes.dl_classifier import (DLClassifier, DLClassifierModel,
                                          DLEstimator, DLModel)

__all__ = ["DLEstimator", "DLModel", "DLClassifier", "DLClassifierModel"]
