"""pyspark-BigDL API compatibility: `bigdl.models.inception`.

Parity: reference pyspark/bigdl/models/inception/inception.py — the
GoogLeNet-v1 builders (inception_layer_v1 block, the no-aux and full
two-aux-head variants). Built here over the SAME compat layer API a
ported user script would use, with the inception block expressed as a
loop over branch configs instead of the reference's unrolled text. The
`t(...)` Table-literal helper and config shapes match the reference so
its call sites work unchanged.
"""

from __future__ import annotations

from bigdl.nn.initialization_method import ConstInitMethod, Xavier, Zeros
from bigdl.nn.layer import (Concat, Dropout, Linear, LogSoftMax, ReLU,
                            Sequential, SpatialAveragePooling,
                            SpatialConvolution, SpatialCrossMapLRN,
                            SpatialMaxPooling, View)


def t(input_t):
    """List -> 1-based dict Table literal (reference helper)."""
    if isinstance(input_t, list):
        return dict(enumerate(input_t, 1))
    return input_t


def _conv(n_in, n_out, k, stride=1, pad=0, name=""):
    return (SpatialConvolution(n_in, n_out, k, k, stride, stride, pad, pad)
            .set_init_method(weight_init_method=Xavier(),
                             bias_init_method=ConstInitMethod(0.1))
            .set_name(name))


def inception_layer_v1(input_size, config, name_prefix=""):
    """One inception block: 1x1 / 3x3 / 5x5 / pool-proj branches
    concatenated on the channel dim (reference inception_layer_v1)."""
    concat = Concat(2)
    # (branch-name, reduce-channels or None, conv kernel, out-channels)
    p = name_prefix
    b1 = Sequential().add(_conv(input_size, config[1][1], 1, name=p + "1x1"))
    b1.add(ReLU(True).set_name(p + "relu_1x1"))
    concat.add(b1)
    b3 = Sequential().add(_conv(input_size, config[2][1], 1,
                                name=p + "3x3_reduce"))
    b3.add(ReLU(True).set_name(p + "relu_3x3_reduce"))
    b3.add(_conv(config[2][1], config[2][2], 3, pad=1, name=p + "3x3"))
    b3.add(ReLU(True).set_name(p + "relu_3x3"))
    concat.add(b3)
    b5 = Sequential().add(_conv(input_size, config[3][1], 1,
                                name=p + "5x5_reduce"))
    b5.add(ReLU(True).set_name(p + "relu_5x5_reduce"))
    b5.add(_conv(config[3][1], config[3][2], 5, pad=2, name=p + "5x5"))
    b5.add(ReLU(True).set_name(p + "relu_5x5"))
    concat.add(b5)
    bp = Sequential().add(SpatialMaxPooling(3, 3, 1, 1, 1, 1, to_ceil=True)
                          .set_name(p + "pool"))
    bp.add(_conv(input_size, config[4][1], 1, name=p + "pool_proj"))
    bp.add(ReLU(True).set_name(p + "relu_pool_proj"))
    concat.add(bp).set_name(p + "output")
    return concat


# per-stage block configs shared by both variants (reference's literals)
_BLOCKS = [
    ("inception_3a/", 192, [[64], [96, 128], [16, 32], [32]]),
    ("inception_3b/", 256, [[128], [128, 192], [32, 96], [64]]),
    ("pool", None, None),
    ("inception_4a/", 480, [[192], [96, 208], [16, 48], [64]]),
    ("inception_4b/", 512, [[160], [112, 224], [24, 64], [64]]),
    ("inception_4c/", 512, [[128], [128, 256], [24, 64], [64]]),
    ("inception_4d/", 512, [[112], [144, 288], [32, 64], [64]]),
    ("inception_4e/", 528, [[256], [160, 320], [32, 128], [128]]),
    ("pool", None, None),
    ("inception_5a/", 832, [[256], [160, 320], [32, 128], [128]]),
    ("inception_5b/", 832, [[384], [192, 384], [48, 128], [128]]),
]


def _stem(model):
    model.add(SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3, 1, False)
              .set_init_method(weight_init_method=Xavier(),
                               bias_init_method=ConstInitMethod(0.1))
              .set_name("conv1/7x7_s2"))
    model.add(ReLU(True).set_name("conv1/relu_7x7"))
    model.add(SpatialMaxPooling(3, 3, 2, 2, to_ceil=True)
              .set_name("pool1/3x3_s2"))
    model.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("pool1/norm1"))
    model.add(_conv(64, 64, 1, name="conv2/3x3_reduce"))
    model.add(ReLU(True).set_name("conv2/relu_3x3_reduce"))
    model.add(_conv(64, 192, 3, pad=1, name="conv2/3x3"))
    model.add(ReLU(True).set_name("conv2/relu_3x3"))
    model.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("conv2/norm2"))
    model.add(SpatialMaxPooling(3, 3, 2, 2, to_ceil=True)
              .set_name("pool2/3x3_s2"))
    return model


def inception_v1_no_aux_classifier(class_num, has_dropout=True):
    model = _stem(Sequential())
    for name, n_in, cfg in _BLOCKS:
        if name == "pool":
            model.add(SpatialMaxPooling(3, 3, 2, 2, to_ceil=True))
        else:
            model.add(inception_layer_v1(n_in, t([t(c) for c in cfg]), name))
    model.add(SpatialAveragePooling(7, 7, 1, 1).set_name("pool5/7x7_s1"))
    if has_dropout:
        model.add(Dropout(0.4).set_name("pool5/drop_7x7_s1"))
    model.add(View([1024], num_input_dims=3))
    model.add(Linear(1024, class_num)
              .set_init_method(weight_init_method=Xavier(),
                               bias_init_method=Zeros())
              .set_name("loss3/classifier"))
    model.add(LogSoftMax().set_name("loss3/loss3"))
    model.reset()
    return model


def inception_v1(class_num, has_dropout=True):
    """Full training variant with the two auxiliary classifier heads —
    delegates to the native builder (bigdl_tpu/models/inception.py keeps
    the aux-head topology) and wraps it in the compat Layer facade."""
    from bigdl.nn.layer import Layer
    from bigdl_tpu.models.inception import Inception_v1
    return Layer.of(Inception_v1(class_num, has_dropout=has_dropout))
