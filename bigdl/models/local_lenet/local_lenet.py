"""pyspark-BigDL API compatibility: `bigdl.models.local_lenet`.

Parity: reference pyspark/bigdl/models/local_lenet/local_lenet.py — the
Spark-free LeNet training entry (the reference's own local-mode path;
tests/test_pyspark_compat.py additionally executes the REFERENCE file
verbatim against this package). `get_mnist` returns plain ndarrays with
1-based labels, exactly the reference contract.
"""

from __future__ import annotations

from bigdl.dataset import mnist


def get_mnist(data_type="train", location="/tmp/mnist"):
    """(features ndarray, 1-based label ndarray) for the split."""
    X, Y = mnist.read_data_sets(location, data_type)
    return X, Y + 1


def train_local(data_path="/tmp/mnist", batch_size=128, max_epoch=2):
    """The reference __main__ body as a callable: build LeNet-5, train
    through the local Optimizer, validate Top1 each epoch."""
    from bigdl.models.lenet.lenet5 import build_model
    from bigdl.nn.criterion import ClassNLLCriterion
    from bigdl.optim.optimizer import (EveryEpoch, MaxEpoch, Optimizer, SGD,
                                       Top1Accuracy)
    from bigdl.util.common import init_engine

    init_engine()
    (X_train, Y_train), (X_test, Y_test) = mnist.load_data(data_path)
    optimizer = Optimizer.create(
        model=build_model(10),
        training_set=(X_train, Y_train),
        criterion=ClassNLLCriterion(),
        optim_method=SGD(learningrate=0.01, learningrate_decay=0.0002),
        end_trigger=MaxEpoch(max_epoch),
        batch_size=batch_size)
    optimizer.set_validation(
        batch_size=batch_size, X_val=X_test, Y_val=Y_test,
        trigger=EveryEpoch(), val_method=[Top1Accuracy()])
    trained_model = optimizer.optimize()
    return trained_model, trained_model.predict_class(X_test)
