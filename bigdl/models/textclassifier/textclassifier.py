"""pyspark-BigDL API compatibility: `bigdl.models.textclassifier`.

Parity: reference pyspark/bigdl/models/textclassifier/textclassifier.py —
the news20 text-CNN/LSTM/GRU classifier. The model builder and the text
helpers keep the reference contract; `analyze_texts` operates on a list
of (text, label) pairs instead of an RDD (declared delta: no Spark).
"""

from __future__ import annotations

import itertools
import re

import numpy as np

from bigdl.nn.layer import (GRU, LSTM, Dropout, Linear, LogSoftMax, ReLU,
                            Recurrent, Select, Sequential, Squeeze,
                            TemporalConvolution, TemporalMaxPooling)
from bigdl.util.common import Sample

# module-level knobs, assigned by the training entry in the reference
model_type = "cnn"
sequence_len = 500
embedding_dim = 200
p = 0.0


def text_to_words(review_text):
    letters_only = re.sub("[^a-zA-Z]", " ", review_text)
    return letters_only.lower().split()


def analyze_texts(data):
    """[(word, (1-based index by desc frequency, count))] over a list of
    (text, label) pairs (reference runs the same aggregation as an RDD
    wordcount)."""
    freq = {}
    for text, _label in data:
        for w in text_to_words(text):
            freq[w] = freq.get(w, 0) + 1
    ordered = sorted(freq.items(), key=lambda wc: -wc[1])
    return [(w, (i + 1, c)) for i, (w, c) in enumerate(ordered)]


def pad(l, fill_value, width):
    if len(l) >= width:
        return l[0:width]
    l.extend([fill_value] * (width - len(l)))
    return l


def to_vec(token, b_w2v, embedding_dim):
    if token in b_w2v:
        return b_w2v[token]
    return pad([], 0, embedding_dim)


def to_sample(vectors, label, embedding_dim):
    flatten_features = list(itertools.chain(*vectors))
    features = np.array(flatten_features, dtype='float').reshape(
        [sequence_len, embedding_dim])
    return Sample.from_ndarray(features, np.array(label))


def build_model(class_num):
    model = Sequential()
    if model_type.lower() == "cnn":
        model.add(TemporalConvolution(embedding_dim, 256, 5)) \
            .add(ReLU()) \
            .add(TemporalMaxPooling(sequence_len - 5 + 1)) \
            .add(Squeeze(2))
    elif model_type.lower() == "lstm":
        model.add(Recurrent().add(LSTM(embedding_dim, 256, p)))
        model.add(Select(2, -1))
    elif model_type.lower() == "gru":
        model.add(Recurrent().add(GRU(embedding_dim, 256, p)))
        model.add(Select(2, -1))
    model.add(Linear(256, 128)) \
        .add(Dropout(0.2)) \
        .add(ReLU()) \
        .add(Linear(128, class_num)) \
        .add(LogSoftMax())
    return model
